"""ServeController: deploy/reconcile/autoscale.

Reference: `serve/_private/controller.py:92` (ServeController actor),
`deployment_state.py` (replica reconciliation), `autoscaling_state.py:261`
+ `autoscaling_policy.py:12` (`_calculate_desired_num_replicas` targets
``target_ongoing_requests`` per replica), `proxy_state.py`.

The controller is an actor; a daemon thread runs the reconcile+autoscale
loop. Handles learn replica membership via ``get_replicas`` (versioned
pull — the long-poll equivalent).
"""

from __future__ import annotations

import math
import threading
import time
import traceback
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.serve.deployment import Application, AutoscalingConfig, Deployment
from ray_tpu.serve.replica import Replica


class _DeploymentState:
    def __init__(self, deployment: Deployment, init_args, init_kwargs):
        self.deployment = deployment
        self.init_args = init_args
        self.init_kwargs = init_kwargs
        self.target_replicas = deployment.num_replicas
        if deployment.autoscaling_config:
            self.target_replicas = max(
                deployment.autoscaling_config.min_replicas,
                min(self.target_replicas,
                    deployment.autoscaling_config.max_replicas))
        self.replicas: List[Any] = []
        self.replica_slots: List[int] = []   # parallel to replicas
        self.version = 0
        self.last_scale_ts = 0.0
        # slot -> (metrics dict, monotonic recv time): PUSHED by replica
        # reporter threads; reconcile/autoscale read this cache and never
        # block on a per-replica RPC (reference: autoscaling_state.py).
        self.metrics_cache: Dict[int, Any] = {}
        self.started_at: Dict[int, float] = {}   # slot -> start time
        # last autoscale decision inputs (status()/tests introspection)
        self.autoscale_info: Dict[str, Any] = {}
        # slot -> actor id hex of the replica the CONTROLLER placed
        # there: reports from any other incarnation (e.g. a killed
        # in-process replica whose reporter thread is still running) are
        # dropped, so a zombie heartbeat can't keep a dead slot healthy.
        self.replica_ids: Dict[int, str] = {}


_CKPT_KEY = b"serve::applications"


class ServeController:
    """Crash-recoverable: deployment specs checkpoint to the GCS KV on
    every deploy/delete; a restarted incarnation restores them and
    re-binds to still-live NAMED replica actors instead of leaking them
    (reference: controller recovery from GCS checkpoints,
    serve/tests/test_controller_crashes.py)."""

    def __init__(self):
        from ray_tpu.serve.deployment_scheduler import DeploymentScheduler
        from ray_tpu.serve.long_poll import LongPollHost
        self._state: Dict[str, _DeploymentState] = {}
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._tick_s = 0.5
        self._report_interval_s = 0.5
        # 6 missed reports before a replica becomes a ping-confirmed
        # death suspect
        self._stale_after_s = 3.0
        self._long_poll = LongPollHost()
        self._scheduler = DeploymentScheduler()
        self._compact_counter = 0
        # name -> (membership version, slot list, depth list) last
        # pushed on the depths:: long-poll key (skip republishing
        # unchanged views; gone slots get their gauge series removed)
        self._depths_published: Dict[str, Any] = {}  #: guarded by self._lock
        # federated queue-pressure signal: previous (sum, count) totals
        # and the last computed per-tick mean — loop-thread only
        self._phase_totals_prev = None
        self._queue_pressure_last = 0.0
        self._recover_from_checkpoint()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    # -- long-poll host (push config propagation) ----------------------
    def listen_for_change(self, keys_to_versions: Dict[str, int]
                          ) -> Dict[str, Any]:
        return self._long_poll.listen(keys_to_versions)

    def _publish_replicas(self, name: str) -> None:
        with self._lock:
            st = self._state.get(name)
            if st is None:
                return
            snapshot = {"replicas": list(st.replicas),
                        "version": st.version}
        self._long_poll.publish(f"replicas::{name}", snapshot)

    # -- crash recovery -------------------------------------------------
    def _kv(self):
        from ray_tpu._private import worker
        rt = worker.global_runtime()
        return rt.gcs if rt is not None and hasattr(rt, "gcs") else None

    def _checkpoint(self) -> None:
        import cloudpickle
        kv = self._kv()
        if kv is None:
            return
        with self._lock:
            specs = {name: (st.deployment, st.init_args, st.init_kwargs,
                            st.target_replicas)
                     for name, st in self._state.items()}
        try:
            kv.kv_put(_CKPT_KEY, cloudpickle.dumps(specs))
        except Exception:
            pass

    def _recover_from_checkpoint(self) -> None:
        import cloudpickle
        kv = self._kv()
        if kv is None:
            return
        try:
            blob = kv.kv_get(_CKPT_KEY)
        except Exception:
            return
        if not blob:
            return
        try:
            specs = cloudpickle.loads(blob)
        except Exception:
            return
        for name, (dep, args, kwargs, target) in specs.items():
            st = _DeploymentState(dep, args, kwargs)
            st.target_replicas = target
            with self._lock:
                self._state[name] = st
            self._reconcile_one(name)

    # -- deploy --------------------------------------------------------
    def deploy_application(self, app: Application,
                           route_name: Optional[str] = None) -> str:
        """Deploy an application graph depth-first; bound Application args
        become DeploymentHandles (model composition)."""
        from ray_tpu.serve.router import DeploymentHandle

        name = route_name or app.deployment.name
        self._deploy_node(app)
        return name

    def _deploy_node(self, app: Application) -> str:
        from ray_tpu.serve.router import DeploymentHandle

        dep = app.deployment
        args = []
        for a in app.args:
            if isinstance(a, Application):
                child = self._deploy_node(a)
                args.append(DeploymentHandle(child, self._self_handle()))
            else:
                args.append(a)
        kwargs = {}
        for k, v in app.kwargs.items():
            if isinstance(v, Application):
                child = self._deploy_node(v)
                kwargs[k] = DeploymentHandle(child, self._self_handle())
            else:
                kwargs[k] = v
        with self._lock:
            st = _DeploymentState(dep, tuple(args), kwargs)
            self._state[dep.name] = st
        self._reconcile_one(dep.name)
        self._checkpoint()
        return dep.name

    def _self_handle(self):
        return ray_tpu.get_actor("serve_controller")

    # -- reconciliation ------------------------------------------------
    def _start_replica(self, st: _DeploymentState, slot: int):
        from ray_tpu._private.task_spec import NodeAffinitySchedulingStrategy
        opts = dict(st.deployment.ray_actor_options or {})
        name = st.deployment.name
        # SPREAD placement across alive nodes (deployment_scheduler.py;
        # reference SPREAD default :34); soft affinity so a full node
        # doesn't block the replica.
        node_hex = self._scheduler.pick_node_for_replica(name)
        if node_hex is not None and "scheduling_strategy" not in opts:
            opts["scheduling_strategy"] = NodeAffinitySchedulingStrategy(
                node_id=node_hex, soft=True)
        replica_cls = ray_tpu.remote(Replica)
        handle = replica_cls.options(
            # Replicas wrap user callables that may own jax/device state
            # (LLM engines); TPU-first placement keeps them with the mesh.
            _in_process=True,
            # Named so a restarted controller re-binds instead of leaking
            # the live replica (crash recovery).
            name=f"SERVE_REPLICA::{name}::{slot}",
            get_if_exists=True,
            max_concurrency=st.deployment.max_ongoing_requests,
            max_restarts=st.deployment.max_restarts, **opts,
        ).remote(st.deployment.func_or_class, st.init_args, st.init_kwargs,
                 st.deployment.user_config,
                 report_to="serve_controller", deployment=name, slot=slot,
                 report_interval_s=self._report_interval_s)
        ray_tpu.get(handle.ping.remote())   # fail fast on ctor errors
        st.started_at[slot] = time.monotonic()
        st.replica_ids[slot] = handle._actor_id.hex()
        st.metrics_cache.pop(slot, None)   # no stale entry for a reused slot
        if node_hex is not None:
            self._scheduler.record(name, handle, node_hex)
        return handle

    def report_metrics(self, name: str, slot: int, m: Dict,
                       actor_id: Optional[str] = None) -> None:
        """Push endpoint for replica reporter threads (reference:
        autoscaling_state.py record_request_metrics_for_replica)."""
        with self._lock:
            st = self._state.get(name)
            if st is not None and (
                    actor_id is None
                    or st.replica_ids.get(slot) == actor_id):
                st.metrics_cache[slot] = (m, time.monotonic())

    def _reconcile_one(self, name: str) -> None:
        victims: List[Any] = []
        with self._lock:
            st = self._state.get(name)
            if st is None:
                return
            target = st.target_replicas
            changed = False
            while len(st.replicas) < target:
                # lowest unused slot: a mid-list removal must NOT make us
                # collide with a live higher slot via get_if_exists
                used = set(st.replica_slots)
                slot = next(i for i in range(target + len(used) + 1)
                            if i not in used)
                st.replicas.append(self._start_replica(st, slot=slot))
                st.replica_slots.append(slot)
                changed = True
            while len(st.replicas) > target:
                victim = st.replicas.pop()
                slot = st.replica_slots.pop()
                # drop the slot's bookkeeping NOW: a report from the
                # still-draining victim must not resurrect the slot
                st.metrics_cache.pop(slot, None)
                st.replica_ids.pop(slot, None)
                st.started_at.pop(slot, None)
                victims.append(victim)
                changed = True
            if changed:
                st.version += 1
            drain_timeout_s = st.deployment.graceful_shutdown_timeout_s
        if changed:
            # publish FIRST so routers stop picking the victims, then
            # drain: their in-flight requests finish instead of burning
            self._publish_replicas(name)
        for victim in victims:
            self._scheduler.forget(name, victim)
            self._drain_replica(victim, drain_timeout_s)

    def _drain_replica(self, victim, timeout_s: float) -> None:
        """Deferred kill for a downscaled replica: a background thread
        polls its reported load and kills only once ongoing+queue hit
        zero (or the graceful window expires). Routers already dropped
        it at the membership publish, so the load only drains."""
        def waiter():
            deadline = time.monotonic() + max(0.0, timeout_s)
            while time.monotonic() < deadline:
                try:
                    m = ray_tpu.get(victim.metrics.remote(), timeout=2)
                except Exception:
                    break           # already dead / unreachable
                if (m.get("ongoing", 0) <= 0
                        and m.get("queue_depth", 0) <= 0):
                    break
                time.sleep(0.1)
            try:
                ray_tpu.kill(victim)
            except Exception:
                pass

        threading.Thread(target=waiter, daemon=True,
                         name="serve-replica-drain").start()

    def _check_health(self, name: str) -> None:
        with self._lock:
            st = self._state.get(name)
            if st is None:
                return
            now = time.monotonic()
            changed = False
            suspects = []
            for r, slot in zip(st.replicas, st.replica_slots):
                entry = st.metrics_cache.get(slot)
                # unseen slot (e.g. re-bound after controller restart):
                # start its staleness clock at this pass
                st.started_at.setdefault(slot, now)
                age = now - entry[1] if entry is not None else \
                    now - st.started_at[slot]
                if age > self._stale_after_s:
                    # no recent push: confirm before declaring it dead
                    # (a replica whose reporter died but whose executor
                    # lives should survive a health pass)
                    suspects.append((r, slot))
        dead = []
        for r, slot in suspects:
            try:
                ray_tpu.get(r.ping.remote(), timeout=5)
            except Exception:
                dead.append(slot)
        with self._lock:
            if dead and st is self._state.get(name):
                # Remove ONLY the ping-confirmed dead slots from the
                # CURRENT lists — replicas added concurrently during the
                # unlocked ping window must survive.
                keep = [(r, slot)
                        for r, slot in zip(st.replicas, st.replica_slots)
                        if slot not in dead]
                st.replicas = [r for r, _ in keep]
                st.replica_slots = [slot for _, slot in keep]
                for slot in dead:
                    st.metrics_cache.pop(slot, None)
                    st.replica_ids.pop(slot, None)
                    st.started_at.pop(slot, None)
                st.version += 1
                changed = True
        if changed:
            self._publish_replicas(name)
            self._reconcile_one(name)

    # -- replica depth snapshots (routers + autoscaler) ----------------
    def _replica_depths_locked(self, st: _DeploymentState) -> List[float]:
        """Positional depth per replica — reported ongoing + engine
        queue backlog from the pushed metrics cache. A stale/unseen
        slot scores 0 (a freshly started replica must attract traffic,
        not repel it). Call under ``self._lock``."""
        now = time.monotonic()
        depths: List[float] = []
        for slot in st.replica_slots:
            entry = st.metrics_cache.get(slot)
            if entry is not None and now - entry[1] <= self._stale_after_s:
                m = entry[0]
                depths.append(float(m.get("ongoing", 0.0))
                              + float(m.get("queue_depth", 0.0)))
            else:
                depths.append(0.0)
        return depths

    def _publish_depths(self, name: str) -> None:
        """Fan the reported depths out to every handle's router on the
        ``depths::<name>`` long-poll key (once per tick, only when the
        view changed) — P2C then scores replicas by cluster-wide load,
        not just handle-local in-flight."""
        with self._lock:
            st = self._state.get(name)
            if st is None:
                return
            depths = self._replica_depths_locked(st)
            version = st.version
            slots = list(st.replica_slots)
            prev = self._depths_published.get(name)
            if prev == (version, slots, depths):
                return
            self._depths_published[name] = (version, slots, depths)
        self._long_poll.publish(f"depths::{name}",
                                {"depths": depths, "version": version})
        try:
            from ray_tpu.util.metrics import Gauge
            gauge = Gauge("ray_tpu_serve_replica_depth",
                          "reported replica depth (ongoing + engine "
                          "queue) per deployment slot")
            for slot, depth in zip(slots, depths):
                gauge.set(depth, tags={"deployment": name,
                                       "slot": str(slot)})
            # a downscaled slot's series must not report its last
            # depth forever
            for slot in set(prev[1] if prev else ()) - set(slots):
                gauge.remove(tags={"deployment": name,
                                   "slot": str(slot)})
        except Exception:
            pass

    def get_depths(self, name: str) -> Dict[str, Any]:
        """Introspection: the current depth view (tests, dashboards)."""
        with self._lock:
            st = self._state.get(name)
            if st is None:
                raise KeyError(f"no deployment named {name!r}")
            return {"version": st.version,
                    "slots": list(st.replica_slots),
                    "depths": self._replica_depths_locked(st)}

    # -- autoscaling ---------------------------------------------------
    def _cluster_queue_totals(self):
        """(sum_seconds, count) of the QUEUE phase of the federated
        ``ray_tpu_task_phase_seconds`` histogram: this process's
        registry merged with every node snapshot the head holds
        (``metrics_get`` — PR 4's federation path)."""
        from ray_tpu.util import metrics as _metrics
        total_sum, total_cnt = 0.0, 0.0
        parts = [({}, _metrics.export_snapshot())]
        parts += _metrics._federated_parts()
        for _extra, entries in parts:
            for e in entries or []:
                if (e.get("name") != "ray_tpu_task_phase_seconds"
                        or e.get("kind") != "histogram"):
                    continue
                for key, _counts, hsum, count in e.get("hist", []):
                    if dict((str(k), v) for k, v in key).get(
                            "phase") != "queue":
                        continue
                    total_sum += hsum
                    total_cnt += count
        return total_sum, total_cnt

    def _queue_pressure_s(self) -> float:
        """Cluster-wide mean task queue-phase seconds since the last
        tick. Best-effort: any failure (no federation, no histogram
        yet, counter reset) reads as zero pressure."""
        try:
            cur = self._cluster_queue_totals()
        except Exception:
            return 0.0
        prev, self._phase_totals_prev = self._phase_totals_prev, cur
        if prev is None:
            return 0.0
        d_sum, d_cnt = cur[0] - prev[0], cur[1] - prev[1]
        if d_cnt <= 0 or d_sum < 0:
            return 0.0
        return d_sum / d_cnt

    def _autoscale_one(self, name: str) -> None:
        with self._lock:
            st = self._state.get(name)
        if st is None or st.deployment.autoscaling_config is None:
            return
        cfg = st.deployment.autoscaling_config
        # Scale signal 1: pushed per-replica DEPTH — ongoing requests
        # plus engine queue backlog; the reconcile loop never issues a
        # per-replica RPC (reference: autoscaling_state.py keeps the
        # controller-side aggregate the same way).
        with self._lock:
            total_load = sum(self._replica_depths_locked(st))
        desired = math.ceil(total_load / cfg.target_ongoing_requests) \
            if cfg.target_ongoing_requests > 0 else cfg.min_replicas
        desired = max(cfg.min_replicas, min(cfg.max_replicas, desired))
        # Scale signal 2: the head's federated metrics — while the
        # cluster-wide queue-phase latency (ray_tpu_task_phase_seconds
        # via metrics_get) stays high, a downscale is vetoed even if
        # the depth counts momentarily dipped.
        pressure = self._queue_pressure_last
        now = time.time()
        with self._lock:
            current = st.target_replicas
            st.autoscale_info = {
                "total_load": round(total_load, 2),
                "desired": desired,
                "queue_pressure_s": round(pressure, 4),
            }
            if desired > current:
                delay = cfg.upscale_delay_s
            elif desired < current:
                # the pressure signal is CLUSTER-wide: only let it veto
                # while this deployment itself still reports load, or an
                # unrelated batch sweep pins an idle deployment at peak
                if (cfg.downscale_queue_guard_s > 0
                        and pressure > cfg.downscale_queue_guard_s
                        and total_load > 0):
                    st.autoscale_info["held"] = "queue_pressure"
                    return
                delay = cfg.downscale_delay_s
            else:
                return
            if now - st.last_scale_ts < delay:
                return
            st.target_replicas = desired
            st.last_scale_ts = now
        self._reconcile_one(name)
        try:
            from ray_tpu.util.metrics import Gauge
            Gauge("ray_tpu_serve_target_replicas",
                  "autoscaler target replica count").set(
                desired, tags={"deployment": name})
        except Exception:
            pass

    def _loop(self) -> None:
        while not self._stop.wait(self._tick_s):
            # The runtime can shut down underneath this daemon thread
            # (test teardown without serve.shutdown()): stop quietly
            # instead of racing replica creation against teardown.
            from ray_tpu._private import worker as _worker

            rt = _worker.global_runtime()
            if rt is None or getattr(rt, "_shutdown", False):
                return
            try:
                # one federated queue-pressure sample per tick, shared
                # by every deployment's autoscale decision
                self._queue_pressure_last = self._queue_pressure_s()
                for name in list(self._state):
                    self._check_health(name)
                    self._autoscale_one(name)
                    self._publish_depths(name)
                self._compact_counter += 1
                if self._compact_counter % 20 == 0:
                    self._maybe_compact()
            except Exception:
                if _worker.global_runtime() is None:
                    return  # teardown race, not a real failure
                traceback.print_exc()

    def _maybe_compact(self) -> None:
        """Migrate the least-loaded node's replicas so the node can be
        released (reference: get_node_to_compact :638). One node per
        pass; the reconcile path recreates replicas elsewhere."""
        node_hex = self._scheduler.get_node_to_compact()
        if node_hex is None:
            return
        doomed = self._scheduler.replicas_on(node_hex)
        if not doomed:
            return
        by_dep = {}
        for deployment, rid in doomed:
            by_dep.setdefault(deployment, set()).add(rid)
        # keep evicted replicas off the compacted node while they are
        # re-placed (otherwise SPREAD immediately picks the now-empty
        # node and compaction churns forever)
        self._scheduler.block_node(node_hex)
        for name, rids in by_dep.items():
            with self._lock:
                st = self._state.get(name)
                if st is None:
                    continue
                keep, evict = [], []
                keep_slots = []
                for r, slot in zip(st.replicas, st.replica_slots):
                    if id(r) in rids:
                        evict.append(r)
                    else:
                        keep.append(r)
                        keep_slots.append(slot)
                if not evict:
                    continue
                st.replicas = keep
                st.replica_slots = keep_slots
                st.version += 1
            for r in evict:
                self._scheduler.forget(name, r)
                try:
                    ray_tpu.kill(r)
                except Exception:
                    pass
            self._publish_replicas(name)
            self._reconcile_one(name)

    # -- introspection (handles, status API) ---------------------------
    def get_replicas(self, name: str) -> Dict[str, Any]:
        with self._lock:
            st = self._state.get(name)
            if st is None:
                raise KeyError(f"no deployment named {name!r}")
            return {"replicas": list(st.replicas), "version": st.version}

    def status(self) -> Dict[str, Any]:
        with self._lock:
            return {
                name: {
                    "target_replicas": st.target_replicas,
                    "num_replicas": len(st.replicas),
                    "version": st.version,
                    "autoscaling": st.deployment.autoscaling_config
                    is not None,
                    # last autoscale decision inputs (depth sum, the
                    # federated queue-pressure sample, any hold)
                    "autoscale": dict(st.autoscale_info),
                    # reported per-replica depth (routing view)
                    "depths": self._replica_depths_locked(st),
                    # slots with a fresh PUSHED metrics entry (replica
                    # reporter heartbeats; the controller never polls)
                    "metrics_fresh": sum(
                        1 for slot in st.replica_slots
                        if (e := st.metrics_cache.get(slot)) is not None
                        and time.monotonic() - e[1] <= self._stale_after_s),
                }
                for name, st in self._state.items()}

    def delete_deployment(self, name: str) -> None:
        with self._lock:
            st = self._state.pop(name, None)
            published = self._depths_published.pop(name, None)
        if published:
            try:
                from ray_tpu.util.metrics import Gauge
                gauge = Gauge("ray_tpu_serve_replica_depth")
                for slot in published[1]:
                    gauge.remove(tags={"deployment": name,
                                       "slot": str(slot)})
            except Exception:
                pass
        self._scheduler.forget_deployment(name)
        if st:
            for r in st.replicas:
                try:
                    ray_tpu.kill(r)
                except Exception:
                    pass
        self._checkpoint()
        self._long_poll.publish(f"replicas::{name}",
                                {"replicas": [], "version": 1 << 30})

    def reconfigure_deployment(self, name: str, user_config: Dict) -> None:
        with self._lock:
            st = self._state.get(name)
            if st is None:
                raise KeyError(name)
            replicas = list(st.replicas)
            st.deployment = st.deployment.options(user_config=user_config)
        ray_tpu.get([r.reconfigure.remote(user_config) for r in replicas])

    def set_target_replicas(self, name: str, n: int) -> None:
        with self._lock:
            st = self._state.get(name)
            if st is None:
                raise KeyError(name)
            st.target_replicas = n
        self._reconcile_one(name)

    def shutdown(self) -> None:
        self._stop.set()
        for name in list(self._state):
            self.delete_deployment(name)

    def ping(self) -> bool:
        return True
