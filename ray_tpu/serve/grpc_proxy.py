"""gRPC proxy for Serve applications.

Reference: ``serve/_private/proxy.py:521`` (gRPCProxy) — the second
ingress plane next to HTTP. A real ``grpc.Server`` (no generated stubs:
the service is registered with generic method handlers and msgpack
request/response bodies, which keeps the wire gRPC/HTTP2 while staying
codegen-free in this build):

    service ray_tpu.serve.ServeAPIService {
      rpc Predict (bytes msgpack) returns (bytes msgpack);
      rpc ListApplications (bytes) returns (bytes);
      rpc Healthz (bytes) returns (bytes);
    }

``Predict`` request map: {"application": str (optional — default app),
"method": str (optional), "args": [...], "kwargs": {...}}.
"""

from __future__ import annotations

import threading
from concurrent import futures
from typing import Any, Callable, Dict, Optional

import msgpack

SERVICE_NAME = "ray_tpu.serve.ServeAPIService"


def _pack(obj: Any) -> bytes:
    return msgpack.packb(obj, use_bin_type=True, default=repr)


def _unpack(blob: bytes) -> Any:
    return msgpack.unpackb(blob, raw=False)


class GrpcProxy:
    """Routes gRPC calls to application ingress handles."""

    def __init__(self, get_handle: Callable[[Optional[str]], Any],
                 list_apps: Callable[[], Dict[str, str]],
                 host: str = "127.0.0.1", port: int = 0):
        import grpc

        self._get_handle = get_handle
        self._list_apps = list_apps

        def predict(request: bytes, context) -> bytes:
            try:
                body = _unpack(request) if request else {}
                handle = self._get_handle(body.get("application"))
                if body.get("method"):
                    handle = handle.options(body["method"])
                # Honor the client's gRPC deadline (the reference proxy
                # propagates it); fall back to 60 s when the client set
                # none. A small margin keeps our timeout error readable
                # instead of racing the transport's DEADLINE_EXCEEDED.
                # The server-side ceiling stops an hour-long client
                # deadline from pinning a proxy worker thread on a
                # wedged replica.
                rem = context.time_remaining()
                wait = (min(max(0.1, rem - 0.5), 600.0)
                        if rem is not None else 60.0)
                result = handle.remote(
                    *body.get("args", []),
                    **body.get("kwargs", {})).result(timeout=wait)
                return _pack({"result": result})
            except TimeoutError as e:
                # same retryable status the streaming path emits
                context.set_code(grpc.StatusCode.DEADLINE_EXCEEDED)
                context.set_details(f"{type(e).__name__}: {e}")
                return _pack({"error": f"{type(e).__name__}: {e}"})
            except Exception as e:  # noqa: BLE001 — shipped to client
                context.set_code(grpc.StatusCode.INTERNAL)
                context.set_details(f"{type(e).__name__}: {e}")
                return _pack({"error": f"{type(e).__name__}: {e}"})

        def predict_stream(request: bytes, context):
            """Server-streaming Predict (reference: the gRPC proxy's
            streaming path next to HTTP SSE): one msgpack frame per
            replica chunk, flushed as produced. Each chunk wait is
            bounded so a wedged replica returns DEADLINE_EXCEEDED
            instead of pinning a server thread forever."""
            import queue as _queue

            try:
                body = _unpack(request) if request else {}
                handle = self._get_handle(body.get("application"))
                if body.get("method"):
                    handle = handle.options(body["method"])
                gen = handle.options(stream=True).remote(
                    *body.get("args", []), **body.get("kwargs", {}))
                # small bound: end-to-end flow control for slow clients
                # (an unbounded queue would buffer the whole stream)
                q: "_queue.Queue" = _queue.Queue(maxsize=8)
                stop = threading.Event()
                _END = object()

                def offer(item) -> bool:
                    while not stop.is_set():
                        try:
                            q.put(item, timeout=0.5)
                            return True
                        except _queue.Full:
                            continue
                    return False

                def pump():
                    try:
                        for chunk in gen:
                            if not offer(("chunk", chunk)):
                                return   # consumer gone: stop reading
                        offer(("end", _END))
                    except BaseException as e:  # noqa: BLE001
                        offer(("err", e))

                threading.Thread(target=pump, daemon=True,
                                 name="grpc-stream-pump").start()
                try:
                    while True:
                        try:
                            kind, item = q.get(timeout=120.0)
                        except _queue.Empty:
                            context.set_code(
                                grpc.StatusCode.DEADLINE_EXCEEDED)
                            context.set_details(
                                "no chunk from the replica within 120s")
                            yield _pack({"error": "chunk timeout"})
                            return
                        if kind == "chunk":
                            yield _pack({"chunk": item})
                        elif kind == "end":
                            yield _pack({"done": True})
                            return
                        else:
                            raise item
                finally:
                    # client cancel / timeout / error: release the pump
                    # (it stops at its next offer/iteration)
                    stop.set()
            except Exception as e:  # noqa: BLE001 — shipped to client
                context.set_code(grpc.StatusCode.INTERNAL)
                context.set_details(f"{type(e).__name__}: {e}")
                yield _pack({"error": f"{type(e).__name__}: {e}"})

        def list_applications(request: bytes, context) -> bytes:
            return _pack({"applications": self._list_apps()})

        def healthz(request: bytes, context) -> bytes:
            return _pack({"status": "ok"})

        identity = lambda x: x  # noqa: E731 — bytes in, bytes out
        handlers = {
            "Predict": grpc.unary_unary_rpc_method_handler(
                predict, request_deserializer=identity,
                response_serializer=identity),
            "PredictStream": grpc.unary_stream_rpc_method_handler(
                predict_stream, request_deserializer=identity,
                response_serializer=identity),
            "ListApplications": grpc.unary_unary_rpc_method_handler(
                list_applications, request_deserializer=identity,
                response_serializer=identity),
            "Healthz": grpc.unary_unary_rpc_method_handler(
                healthz, request_deserializer=identity,
                response_serializer=identity),
        }
        self._executor = futures.ThreadPoolExecutor(max_workers=16)
        self._server = grpc.server(self._executor)
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(SERVICE_NAME,
                                                  handlers),))
        self.port = self._server.add_insecure_port(f"{host}:{port}")
        self._server.start()

    def stop(self, grace: float = 0.5) -> None:
        self._server.stop(grace)
        # grpc does NOT shut down a caller-provided executor; its
        # non-daemon threads would keep the process alive at exit
        self._executor.shutdown(wait=False)


class GrpcServeClient:
    """Client helper for the proxy (tests / SDK parity)."""

    def __init__(self, address: str):
        import grpc

        self._channel = grpc.insecure_channel(address)
        identity = lambda x: x  # noqa: E731
        base = f"/{SERVICE_NAME}"
        self._predict = self._channel.unary_unary(
            f"{base}/Predict", request_serializer=identity,
            response_deserializer=identity)
        self._predict_stream_rpc = self._channel.unary_stream(
            f"{base}/PredictStream", request_serializer=identity,
            response_deserializer=identity)
        self._list = self._channel.unary_unary(
            f"{base}/ListApplications", request_serializer=identity,
            response_deserializer=identity)
        self._healthz = self._channel.unary_unary(
            f"{base}/Healthz", request_serializer=identity,
            response_deserializer=identity)

    def predict(self, *args, application: Optional[str] = None,
                method: Optional[str] = None,
                timeout: Optional[float] = None, **kwargs) -> Any:
        """``timeout`` becomes the gRPC deadline; the proxy bounds the
        replica wait by it (minus a margin) server-side."""
        import grpc

        body = {"args": list(args), "kwargs": kwargs}
        if application:
            body["application"] = application
        if method:
            body["method"] = method
        try:
            out = _unpack(self._predict(_pack(body), timeout=timeout))
        except grpc.RpcError as e:
            # keep the status code visible for retry policies
            raise RuntimeError(
                f"{e.code().name}: {e.details()}") from None
        if "error" in out:
            raise RuntimeError(out["error"])
        return out["result"]

    def predict_stream(self, *args, application: Optional[str] = None,
                       method: Optional[str] = None, **kwargs):
        """Yield chunks as the replica produces them (server streaming)."""
        import grpc

        body = {"args": list(args), "kwargs": kwargs}
        if application:
            body["application"] = application
        if method:
            body["method"] = method
        try:
            for frame in self._predict_stream_rpc(_pack(body)):
                out = _unpack(frame)
                if "error" in out:
                    raise RuntimeError(out["error"])
                if out.get("done"):
                    return
                yield out["chunk"]
        except grpc.RpcError as e:
            raise RuntimeError(e.details()) from None

    def list_applications(self) -> Dict[str, str]:
        return _unpack(self._list(b""))["applications"]

    def healthz(self) -> bool:
        return _unpack(self._healthz(b""))["status"] == "ok"

    def close(self) -> None:
        self._channel.close()


_proxy: Optional[GrpcProxy] = None
_lock = threading.Lock()


def start_grpc_proxy(port: int = 0) -> int:
    """Start (or return) the process-wide gRPC proxy; returns its port."""
    global _proxy
    with _lock:
        if _proxy is None:
            import atexit

            from ray_tpu.serve import api as serve_api

            handles: Dict[str, Any] = {}
            hlock = threading.Lock()

            def get_handle(app_name: Optional[str]):
                # one handle (and thus ONE long-poll listener) per app —
                # invalidated when the app's ingress deployment changes
                # (delete + redeploy must not route through a stale
                # handle)
                name = app_name or "default"
                ingress = serve_api._apps.get(name)
                if ingress is None:
                    raise KeyError(name)
                with hlock:
                    entry = handles.get(name)
                    if entry is None or entry[0] != ingress:
                        entry = (ingress, serve_api.get_app_handle(name))
                        handles[name] = entry
                    return entry[1]

            def list_apps():
                return dict(serve_api._apps)

            _proxy = GrpcProxy(get_handle, list_apps, port=port)
            atexit.register(stop_grpc_proxy)
        return _proxy.port


def stop_grpc_proxy() -> None:
    global _proxy
    with _lock:
        if _proxy is not None:
            _proxy.stop()
            _proxy = None
