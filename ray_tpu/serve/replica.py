"""Replica actor: hosts the user callable (reference:
`serve/_private/replica.py:918,1028` ReplicaActor + UserCallableWrapper).

Runs with ``max_concurrency = max_ongoing_requests`` so concurrent
requests interleave; tracks ongoing/total counters that feed autoscaling.
"""

from __future__ import annotations

import inspect
import threading
import time
from typing import Any, Dict, Optional


class Replica:
    def __init__(self, func_or_class, init_args, init_kwargs,
                 user_config: Optional[Dict] = None,
                 report_to: Optional[str] = None,
                 deployment: Optional[str] = None,
                 slot: Optional[int] = None,
                 report_interval_s: float = 1.0):
        self._lock = threading.Lock()
        self._ongoing = 0
        self._total = 0
        self._user_config = user_config
        if inspect.isclass(func_or_class):
            self._callable = func_or_class(*init_args, **init_kwargs)
            if user_config is not None and hasattr(
                    self._callable, "reconfigure"):
                self._callable.reconfigure(user_config)
        else:
            self._callable = func_or_class
        # Push-based metrics (reference: autoscaling_state.py — replicas
        # REPORT running/queued counts; the controller never polls): a
        # reporter thread pushes ongoing/total to the named controller,
        # doubling as the liveness heartbeat for health checks.
        if report_to is not None:
            import ray_tpu
            self._actor_id = ray_tpu.get_runtime_context().get_actor_id()
            self._generation = self._own_restart_count()
            threading.Thread(
                target=self._report_loop,
                args=(report_to, deployment, slot,
                      max(0.1, report_interval_s)),
                daemon=True, name=f"replica-report-{deployment}-{slot}",
            ).start()

    def _own_restart_count(self) -> Optional[int]:
        try:
            from ray_tpu._private import worker
            from ray_tpu._private.ids import ActorID
            info = worker.global_runtime().gcs.get_actor_info(
                ActorID.from_hex(self._actor_id))
            return info.num_restarts if info is not None else None
        except Exception:
            return None

    def _still_current(self) -> bool:
        """False once THIS incarnation's actor is dead or restarted —
        the instance's threads outlive an in-process actor kill, and a
        zombie heartbeat would keep a dead slot looking healthy."""
        if self._actor_id is None:
            return True   # no identity available: report unconditionally
        try:
            from ray_tpu._private import worker
            from ray_tpu._private.ids import ActorID
            info = worker.global_runtime().gcs.get_actor_info(
                ActorID.from_hex(self._actor_id))
        except Exception:
            return True   # runtime unavailable ≠ dead; keep reporting
        if info is None:
            return False
        state = getattr(info, "state", None)
        if state is not None and getattr(state, "name", "") == "DEAD":
            return False
        if self._generation is None:
            # generation unknown (GCS unavailable at construction):
            # state alone decides — a healthy replica must keep reporting
            return True
        return info.num_restarts == self._generation

    def _report_loop(self, controller_name: str, deployment: str,
                     slot: int, interval: float) -> None:
        import ray_tpu
        controller = None
        while True:
            time.sleep(interval)
            if not self._still_current():
                return
            try:
                if controller is None:
                    controller = ray_tpu.get_actor(controller_name)
                controller.report_metrics.remote(
                    deployment, slot, self.metrics(),
                    actor_id=self._actor_id)
            except Exception:
                controller = None  # controller restarting: re-resolve

    def reconfigure(self, user_config: Dict) -> None:
        if hasattr(self._callable, "reconfigure"):
            self._callable.reconfigure(user_config)
        self._user_config = user_config

    def handle_request(self, method_name: str, args, kwargs) -> Any:
        with self._lock:
            self._ongoing += 1
            self._total += 1
        try:
            if method_name == "__call__":
                target = self._callable   # function, or instance __call__
            else:
                target = getattr(self._callable, method_name)
            return target(*args, **kwargs)
        finally:
            with self._lock:
                self._ongoing -= 1

    def handle_request_streaming(self, method_name: str, args, kwargs):
        """Generator variant (reference: replica.py:1028
        ``handle_request_streaming``): invoked with
        ``num_returns="streaming"`` so each yielded chunk is sealed as
        its own object and reported to the caller as it is produced —
        the consumer sees chunk 1 before the handler returns. A
        non-generator result degrades to a single-chunk stream."""
        with self._lock:
            self._ongoing += 1
            self._total += 1
        try:
            if method_name == "__call__":
                target = self._callable
            else:
                target = getattr(self._callable, method_name)
            result = target(*args, **kwargs)
            # Stream generators/iterators chunk-wise; any plain value —
            # including iterables like ndarray/list — stays ONE chunk.
            if inspect.isgenerator(result) or (
                    hasattr(result, "__next__")
                    and hasattr(result, "__iter__")):
                yield from result
            else:
                yield result
        finally:
            with self._lock:
                self._ongoing -= 1

    def metrics(self) -> Dict[str, float]:
        """Pushed to the controller by the reporter thread. ``ongoing``
        counts requests inside the replica; ``queue_depth`` is extra
        backlog the user callable reports through an optional
        ``queue_depth()`` method (e.g. an LLM engine's waiting queue —
        requests admitted but not yet holding a decode slot). The
        controller publishes ``ongoing + queue_depth`` to routers and
        feeds both to autoscaling."""
        with self._lock:
            ongoing, total = self._ongoing, self._total
        queue_depth = 0.0
        probe = getattr(self._callable, "queue_depth", None)
        if callable(probe):
            try:
                queue_depth = float(probe())
            except Exception:
                queue_depth = 0.0   # a broken probe must not kill reports
        return {"ongoing": ongoing, "total": total,
                "queue_depth": queue_depth, "ts": time.time()}

    def ping(self) -> bool:
        return True
