"""Replica actor: hosts the user callable (reference:
`serve/_private/replica.py:918,1028` ReplicaActor + UserCallableWrapper).

Runs with ``max_concurrency = max_ongoing_requests`` so concurrent
requests interleave; tracks ongoing/total counters that feed autoscaling.
"""

from __future__ import annotations

import inspect
import threading
import time
from typing import Any, Dict, Optional


class Replica:
    def __init__(self, func_or_class, init_args, init_kwargs,
                 user_config: Optional[Dict] = None):
        self._lock = threading.Lock()
        self._ongoing = 0
        self._total = 0
        self._user_config = user_config
        if inspect.isclass(func_or_class):
            self._callable = func_or_class(*init_args, **init_kwargs)
            if user_config is not None and hasattr(
                    self._callable, "reconfigure"):
                self._callable.reconfigure(user_config)
        else:
            self._callable = func_or_class

    def reconfigure(self, user_config: Dict) -> None:
        if hasattr(self._callable, "reconfigure"):
            self._callable.reconfigure(user_config)
        self._user_config = user_config

    def handle_request(self, method_name: str, args, kwargs) -> Any:
        with self._lock:
            self._ongoing += 1
            self._total += 1
        try:
            if method_name == "__call__":
                target = self._callable   # function, or instance __call__
            else:
                target = getattr(self._callable, method_name)
            return target(*args, **kwargs)
        finally:
            with self._lock:
                self._ongoing -= 1

    def handle_request_streaming(self, method_name: str, args, kwargs):
        """Generator variant (reference: replica.py:1028
        ``handle_request_streaming``): invoked with
        ``num_returns="streaming"`` so each yielded chunk is sealed as
        its own object and reported to the caller as it is produced —
        the consumer sees chunk 1 before the handler returns. A
        non-generator result degrades to a single-chunk stream."""
        with self._lock:
            self._ongoing += 1
            self._total += 1
        try:
            if method_name == "__call__":
                target = self._callable
            else:
                target = getattr(self._callable, method_name)
            result = target(*args, **kwargs)
            # Stream generators/iterators chunk-wise; any plain value —
            # including iterables like ndarray/list — stays ONE chunk.
            if inspect.isgenerator(result) or (
                    hasattr(result, "__next__")
                    and hasattr(result, "__iter__")):
                yield from result
            else:
                yield result
        finally:
            with self._lock:
                self._ongoing -= 1

    def metrics(self) -> Dict[str, float]:
        with self._lock:
            return {"ongoing": self._ongoing, "total": self._total,
                    "ts": time.time()}

    def ping(self) -> bool:
        return True
