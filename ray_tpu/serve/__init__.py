"""ray_tpu.serve — model serving.

Reference: Ray Serve (`python/ray/serve`, SURVEY.md §2.2, §3.5): three
planes — controller actor (deploy/reconcile/autoscale), proxies
(HTTP → handle), replicas (user callables) — plus P2C request routing,
dynamic batching and model composition via deployment handles.
"""

from ray_tpu.serve.api import (delete, get_app_handle,
                               get_deployment_handle, run, shutdown,
                               start_http_proxy, status)
from ray_tpu.serve.batching import batch
from ray_tpu.serve.multiplex import (get_multiplexed_model_id, multiplexed)
from ray_tpu.serve.deployment import (Application, AutoscalingConfig,
                                      Deployment, deployment)
from ray_tpu.serve.router import DeploymentHandle, DeploymentResponse
from ray_tpu.serve.schema import run_config

__all__ = [
    "deployment", "Deployment", "Application", "AutoscalingConfig",
    "run", "shutdown", "status", "delete", "get_deployment_handle",
    "get_app_handle", "start_http_proxy",
    "batch", "DeploymentHandle", "DeploymentResponse",
    "multiplexed", "get_multiplexed_model_id", "run_config",
]
