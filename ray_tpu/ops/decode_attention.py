"""Ragged decode attention: one query token vs a length-bounded KV cache.

Reference capability: vLLM's paged-attention CUDA kernel (outside the
reference tree) — decode must NOT pay for the full ``max_seq`` cache when
a slot has only ``length`` tokens. TPU-native design:

- ``ragged_decode_attention`` — dispatcher (XLA masked fallback or the
  Pallas kernel).
- ``ragged_decode_attention_pallas`` — flash-style online-softmax over
  KV blocks, grid (batch, kv_block). Lengths ride SCALAR PREFETCH
  (``pltpu.PrefetchScalarGridSpec``): the KV BlockSpec index map clamps
  block indices past a slot's length to the last valid block, so skipped
  iterations issue NO new DMA (same window re-used), and ``pl.when``
  skips their compute — the per-step cost tracks ``ceil(length/BK)``
  blocks, not ``max_seq``. Accumulation lives in f32 VMEM scratch across
  the sequentially-iterated kv-block dimension.

Shapes: q [B, H, D]; k/v [B, S, Hkv, D]; lengths [B]. GQA KV heads are
repeated up front.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from ray_tpu.ops.attention import NEG_INF, _repeat_kv


def ragged_decode_attention_reference(q, k, v, lengths, *,
                                      scale: Optional[float] = None):
    """Masked XLA fallback: attends over all S with positions >= length
    masked out. [B,H,D] x [B,S,Hkv,D] -> [B,H,D]."""
    head_dim = q.shape[-1]
    scale = scale if scale is not None else head_dim ** -0.5
    k = _repeat_kv(k, q.shape[1])
    v = _repeat_kv(v, q.shape[1])
    s = jnp.einsum("bhd,bshd->bhs", q, k,
                   preferred_element_type=jnp.float32) * scale
    mask = jnp.arange(k.shape[1])[None, :] < lengths[:, None]   # [B,S]
    s = jnp.where(mask[:, None, :], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhs,bshd->bhd", p.astype(v.dtype), v)


def _decode_kernel(lens_ref, q_ref, k_ref, v_ref, o_ref,
                   m_ref, l_ref, acc_ref, *, block_k: int, scale: float,
                   num_kb: int):
    import jax.experimental.pallas as pl

    b = pl.program_id(0)
    kb = pl.program_id(1)
    length = lens_ref[b]

    @pl.when(kb == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    start = kb * block_k

    @pl.when(start < length)
    def _compute():
        q = q_ref[0].astype(jnp.float32)               # [H, D]
        k = k_ref[0].astype(jnp.float32)               # [BK, H, D]
        v = v_ref[0].astype(jnp.float32)
        s = jnp.einsum("hd,khd->hk", q, k) * scale     # [H, BK]
        idx = start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(idx < length, s, NEG_INF)
        m_prev = m_ref[:, :1]                          # [H, 1]
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                         # [H, BK]
        l_new = alpha * l_prev + jnp.sum(p, -1, keepdims=True)
        acc_ref[...] = (acc_ref[...] * alpha
                        + jnp.einsum("hk,khd->hd", p, v))
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(kb == num_kb - 1)
    def _finish():
        denom = l_ref[:, :1]
        denom = jnp.where(denom == 0.0, 1.0, denom)
        o_ref[0] = (acc_ref[...] / denom).astype(o_ref.dtype)


@functools.partial(jax.jit,
                   static_argnames=("block_k", "scale", "interpret"))
def ragged_decode_attention_pallas(q, k, v, lengths, *,
                                   block_k: int = 128,
                                   scale: Optional[float] = None,
                                   interpret: bool = False):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    head_dim = q.shape[-1]
    scale = scale if scale is not None else head_dim ** -0.5
    k = _repeat_kv(k, q.shape[1])
    v = _repeat_kv(v, q.shape[1])
    B, H, D = q.shape
    S = k.shape[1]
    bk = min(block_k, S)
    num_kb = pl.cdiv(S, bk)
    if S % bk != 0:
        pad = num_kb * bk - S
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
    lengths = lengths.astype(jnp.int32)

    def kv_map(b, kb, lens):
        # past-length blocks CLAMP to the last valid block: the window
        # doesn't move, so the skipped iteration costs no new DMA
        last_valid = jnp.maximum(
            (lens[b] + bk - 1) // bk - 1, 0)
        return (b, jnp.minimum(kb, last_valid), 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B, num_kb),
        in_specs=[
            pl.BlockSpec((1, H, D), lambda b, kb, lens: (b, 0, 0)),
            pl.BlockSpec((1, bk, H, D), kv_map),
            pl.BlockSpec((1, bk, H, D), kv_map),
        ],
        out_specs=pl.BlockSpec((1, H, D), lambda b, kb, lens: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H, 128), jnp.float32),
            pltpu.VMEM((H, 128), jnp.float32),
            pltpu.VMEM((H, D), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_decode_kernel, block_k=bk, scale=scale,
                          num_kb=num_kb),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, D), q.dtype),
        interpret=interpret,
    )(lengths, q, k, v)
    return out


def ragged_decode_attention(q, k, v, lengths, *, impl: str = "xla",
                            scale: Optional[float] = None,
                            interpret: bool = False):
    if impl == "pallas":
        return ragged_decode_attention_pallas(q, k, v, lengths,
                                              scale=scale,
                                              interpret=interpret)
    return ragged_decode_attention_reference(q, k, v, lengths,
                                             scale=scale)
