"""Paged decode attention: one query token vs a block-pool KV cache.

Reference capability: vLLM's paged-attention kernel (the engine behind
`ray.llm`'s serving tier, outside the reference tree; config surface at
`python/ray/llm/_internal/serve/deployments/llm/vllm/vllm_models.py:126`).
TPU-native design:

- K/V live in a shared BLOCK POOL ``[num_blocks, block_size, Hkv, D]``;
  each slot's logical sequence is a list of physical block ids (the
  block table). Blocks are immutable once full, so identical prompt
  prefixes SHARE physical blocks (see ``llm/paged_cache.py``).
- ``paged_decode_attention`` — dispatcher (XLA gather fallback or the
  Pallas kernel).
- ``paged_decode_attention_pallas`` — flash-style online-softmax,
  grid (batch, logical_block). The block table and lengths ride scalar
  prefetch: the KV BlockSpec index map translates LOGICAL block ``kb``
  of slot ``b`` to PHYSICAL ``tables[b, kb]`` — the kernel never sees
  more than ``ceil(length/bs)`` blocks per slot, and no gather of the
  pool into a dense cache ever materializes.
- GQA stays grouped: the pool keeps Hkv heads; q is repeated only
  inside the per-block VMEM tile, never in HBM.

Shapes: q [B, H, D]; k_pool/v_pool [NB, bs, Hkv, D];
block_tables [B, MAXB] int32 (physical ids; entries past a slot's
length are ignored); lengths [B] int32.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from ray_tpu.ops.attention import NEG_INF
from ray_tpu.ops.decode_attention import ragged_decode_attention_reference


def paged_decode_attention_reference(q, k_pool, v_pool, block_tables,
                                     lengths, *,
                                     scale: Optional[float] = None):
    """XLA fallback: gather the slot's blocks into a dense view, then
    run the masked ragged reference. One extra HBM round-trip of the
    active context vs the Pallas path — correct everywhere, slower."""
    B, maxb = block_tables.shape
    bs = k_pool.shape[1]
    k = k_pool[block_tables]                     # [B, MAXB, bs, Hkv, D]
    v = v_pool[block_tables]
    k = k.reshape(B, maxb * bs, *k.shape[3:])
    v = v.reshape(B, maxb * bs, *v.shape[3:])
    return ragged_decode_attention_reference(q, k, v, lengths, scale=scale)


def _paged_kernel(lens_ref, tables_ref, q_ref, k_ref, v_ref, o_ref,
                  m_ref, l_ref, acc_ref, *, block_size: int, scale: float,
                  num_kb: int, groups: int):
    import jax.experimental.pallas as pl

    b = pl.program_id(0)
    kb = pl.program_id(1)
    length = lens_ref[b]

    @pl.when(kb == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    start = kb * block_size

    @pl.when(start < length)
    def _compute():
        q = q_ref[0].astype(jnp.float32)               # [H, D]
        k = k_ref[0].astype(jnp.float32)               # [bs, Hkv, D]
        v = v_ref[0].astype(jnp.float32)
        if groups > 1:   # repeat KV heads inside the VMEM tile only
            bs_, hkv, d = k.shape
            k = jnp.broadcast_to(k[:, :, None, :],
                                 (bs_, hkv, groups, d)).reshape(
                                     bs_, hkv * groups, d)
            v = jnp.broadcast_to(v[:, :, None, :],
                                 (bs_, hkv, groups, d)).reshape(
                                     bs_, hkv * groups, d)
        s = jnp.einsum("hd,khd->hk", q, k) * scale     # [H, bs]
        idx = start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(idx < length, s, NEG_INF)
        m_prev = m_ref[:, :1]                          # [H, 1]
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                         # [H, bs]
        l_new = alpha * l_prev + jnp.sum(p, -1, keepdims=True)
        acc_ref[...] = (acc_ref[...] * alpha
                        + jnp.einsum("hk,khd->hd", p, v))
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(kb == num_kb - 1)
    def _finish():
        denom = l_ref[:, :1]
        denom = jnp.where(denom == 0.0, 1.0, denom)
        o_ref[0] = (acc_ref[...] / denom).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("scale", "interpret"))
def paged_decode_attention_pallas(q, k_pool, v_pool, block_tables,
                                  lengths, *,
                                  scale: Optional[float] = None,
                                  interpret: bool = False):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, D = q.shape
    NB, bs, Hkv, _ = k_pool.shape
    maxb = block_tables.shape[1]
    groups = H // Hkv
    scale = scale if scale is not None else D ** -0.5
    lengths = lengths.astype(jnp.int32)
    block_tables = block_tables.astype(jnp.int32)

    def kv_map(b, kb, lens, tables):
        # logical->physical translation; past-length logical blocks clamp
        # to the slot's last valid entry so the skipped iteration re-DMAs
        # one already-resident block at worst
        last_valid = jnp.maximum((lens[b] + bs - 1) // bs - 1, 0)
        return (tables[b, jnp.minimum(kb, last_valid)], 0, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, maxb),
        in_specs=[
            pl.BlockSpec((1, H, D), lambda b, kb, lens, tables: (b, 0, 0)),
            pl.BlockSpec((1, bs, Hkv, D), kv_map),
            pl.BlockSpec((1, bs, Hkv, D), kv_map),
        ],
        out_specs=pl.BlockSpec((1, H, D),
                               lambda b, kb, lens, tables: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H, 128), jnp.float32),
            pltpu.VMEM((H, 128), jnp.float32),
            pltpu.VMEM((H, D), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_paged_kernel, block_size=bs, scale=scale,
                          num_kb=maxb, groups=groups),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, D), q.dtype),
        interpret=interpret,
    )(lengths, block_tables, q, k_pool, v_pool)
    return out


def paged_decode_attention(q, k_pool, v_pool, block_tables, lengths, *,
                           impl: str = "xla",
                           scale: Optional[float] = None,
                           interpret: Optional[bool] = None):
    if impl == "pallas":
        if interpret is None:
            # same contract as the flash kernel: off-TPU the SAME
            # kernel logic runs under the Pallas interpreter
            from ray_tpu.ops.attention import _interpret_default
            interpret = _interpret_default()
        return paged_decode_attention_pallas(
            q, k_pool, v_pool, block_tables, lengths, scale=scale,
            interpret=interpret)
    return paged_decode_attention_reference(
        q, k_pool, v_pool, block_tables, lengths, scale=scale)
