"""Explicit capacity-bounded expert-parallel MoE dispatch (all-to-all).

SURVEY.md §2.3: EP is absent in the reference (vLLM internals handle it);
this is native design. Two selectable schemes in :class:`MoEModel`:

- ``einsum`` (models/moe.py): dense one-hot dispatch/combine einsums;
  XLA's SPMD partitioner turns the [T,E,C]x[T,D] contractions into
  collectives. Zero custom communication code, but the compiler chooses
  the schedule.
- ``alltoall`` (this module): GShard-style explicit dispatch inside
  shard_map — tokens are bucketed per expert with a hard capacity,
  buffers cross the ``ep`` axis as two `jax.lax.all_to_all` collectives
  (dispatch and return), and expert FFNs run exactly where their weights
  live. The communication volume is explicit and capacity-bounded:
  2 * E * C_local * D per device per layer, independent of routing skew.

Sharding contract (enforced by the shard_map specs): tokens arrive
sharded [batch -> (dp, fsdp), seq -> (sp, ep)], expert weights sharded
[E -> ep]. Expert FFN weights are NOT additionally tensor-parallel in
this path — use the einsum scheme when tp-sharded experts matter more
than explicit dispatch.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp


def topk_dispatch(xf, router, num_experts: int, top_k: int,
                   capacity: int, z_coef: float, lb_coef: float):
    """Shared router math: returns (dispatch [T,E,C] bool,
    combine [T,E,C] f32, aux scalar)."""
    logits = jnp.einsum("td,de->te", xf.astype(jnp.float32), router)
    probs = jax.nn.softmax(logits, axis=-1)
    z = jax.scipy.special.logsumexp(logits, axis=-1)
    z_loss = jnp.mean(z ** 2) * z_coef
    me = jnp.mean(probs, axis=0)
    top1 = jnp.argmax(probs, axis=-1)
    ce = jnp.mean(jax.nn.one_hot(top1, num_experts), axis=0)
    aux = z_loss + lb_coef * num_experts * jnp.sum(me * ce)

    gate_vals, gate_idx = jax.lax.top_k(probs, top_k)
    gate_vals = gate_vals / jnp.maximum(
        jnp.sum(gate_vals, -1, keepdims=True), 1e-9)
    T = xf.shape[0]
    combine = jnp.zeros((T, num_experts, capacity), jnp.float32)
    dispatch = jnp.zeros((T, num_experts, capacity), jnp.bool_)
    # Slot positions must be unique per expert ACROSS the k passes:
    # choice-k tokens start after every earlier pass's assignments to the
    # same expert (GShard top-2 priority order), or two tokens land in
    # one slot and the expert sees their SUM.
    expert_count = jnp.zeros((num_experts,), jnp.float32)
    for j in range(top_k):
        onehot = jax.nn.one_hot(gate_idx[:, j], num_experts)
        pos_in_pass = jnp.cumsum(onehot, axis=0) - onehot
        pos = jnp.sum((pos_in_pass + expert_count[None, :]) * onehot,
                      axis=-1)
        expert_count = expert_count + jnp.sum(onehot, axis=0)
        in_cap = pos < capacity
        pos_oh = jax.nn.one_hot(pos.astype(jnp.int32), capacity)
        slot = onehot[:, :, None] * pos_oh[:, None, :]
        slot = slot * in_cap[:, None, None]
        dispatch = dispatch | (slot > 0)
        combine = combine + slot * gate_vals[:, j][:, None, None]
    return dispatch, combine, aux


def expert_alltoall_ffn(h, router, e_gate, e_up, e_down, mesh, *,
                        num_experts: int, top_k: int,
                        capacity_factor: float, z_coef: float,
                        lb_coef: float, dtype,
                        axis_name: str = "ep") -> Tuple[jax.Array,
                                                        jax.Array]:
    """MoE FFN with explicit expert all-to-all over ``axis_name``.

    h: [B, S, D] (global, inside pjit). router: [D, E].
    e_gate/e_up: [E, D, F]; e_down: [E, F, D].
    Returns (out [B, S, D], aux [n_shards] — mean it for the loss).
    """
    from jax.sharding import PartitionSpec as P

    from ray_tpu.parallel.mesh import shard_map_compat

    ep = mesh.shape.get(axis_name, 1)

    def body(x, rtr, eg, eu, ed):
        # x: [B_l, S_l, D] local; eg/eu/ed: [E_l, D|F, F|D] local experts
        B_l, S_l, D = x.shape
        T_l = B_l * S_l
        C = max(1, int(capacity_factor * T_l * top_k / num_experts))
        xf = x.reshape(T_l, D)
        dispatch, combine, aux = topk_dispatch(
            xf, rtr, num_experts, top_k, C, z_coef, lb_coef)
        if ep > 1:
            aux = jax.lax.pmean(aux, axis_name)

        # bucket per GLOBAL expert: [E, C, D]
        expert_in = jnp.einsum("tec,td->ecd", dispatch.astype(dtype),
                               xf.astype(dtype))
        if ep > 1:
            # dispatch all-to-all: [E=ep*E_l, C, D] -> [E_l, ep*C, D]
            expert_in = jax.lax.all_to_all(
                expert_in, axis_name, split_axis=0, concat_axis=1,
                tiled=True)
        gate = jnp.einsum("ecd,edf->ecf", expert_in, eg.astype(dtype))
        up = jnp.einsum("ecd,edf->ecf", expert_in, eu.astype(dtype))
        act = jax.nn.silu(gate) * up
        out = jnp.einsum("ecf,efd->ecd", act, ed.astype(dtype))
        if ep > 1:
            # return all-to-all: [E_l, ep*C, D] -> [E, C, D]
            out = jax.lax.all_to_all(out, axis_name, split_axis=1,
                                     concat_axis=0, tiled=True)
        y = jnp.einsum("tec,ecd->td", combine.astype(dtype), out)
        return y.reshape(B_l, S_l, D), aux.reshape(1)

    present = set(mesh.shape.keys())
    batch_axes = tuple(a for a in ("dp", "fsdp") if a in present)
    seq_axes = tuple(a for a in ("sp", axis_name) if a in present)
    x_spec = P(batch_axes or None, seq_axes or None, None)
    w_spec = P(axis_name if axis_name in present else None, None, None)
    aux_spec = P(batch_axes + seq_axes or None)
    fn = shard_map_compat(
        body, mesh, (x_spec, P(None, None), w_spec, w_spec, w_spec),
        (x_spec, aux_spec))
    return fn(h, router, e_gate, e_up, e_down)
