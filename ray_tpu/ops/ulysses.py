"""Ulysses (DeepSpeed-style) sequence parallelism: all-to-all head/seq swap.

SURVEY.md §5.7 requires BOTH context-parallel schemes natively (the
reference has neither — its long-sequence story is delegated to vLLM /
DeepSpeed wrappers):

- ring attention (:mod:`ray_tpu.ops.ring_attention`): K/V rotate around
  the ``sp`` ring via ``ppermute``; communication is O(S·D) per step and
  overlaps with compute. Best when heads are few or already sharded.
- Ulysses (this module): two ``all_to_all`` collectives swap the sharded
  dimension — devices trade their sequence shard for a head shard, run
  ordinary FULL-sequence attention on their subset of heads, and swap
  back. Communication is 2 all-to-alls of the activations; attention
  itself is completely local, so any local kernel (XLA fused attention,
  Pallas flash) applies unchanged. Best when H is divisible by sp and the
  per-device full-sequence fits HBM.

TPU mapping: `jax.lax.all_to_all` over a mesh axis lowers to an ICI
all-to-all; on a torus this rides the same links as the ring but as one
fused transfer. Both schemes are selectable per-model
(``LlamaConfig.attention_impl``).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                      axis_name: str = "sp", causal: bool = True,
                      scale: Optional[float] = None) -> jax.Array:
    """Attention over a sequence sharded on ``axis_name``.

    Call inside shard_map with q/k/v per-device chunks
    [B, S_local, H|H_kv, D]. Requires H % sp == 0 (and H_kv % sp == 0, so
    grouped-query K/V are repeated up to H first when needed).
    """
    from ray_tpu.ops.attention import (_repeat_kv, axis_size,
                                       blockwise_attention)

    sp = axis_size(axis_name)
    heads = q.shape[2]
    if sp == 1:
        k = _repeat_kv(k, heads)
        v = _repeat_kv(v, heads)
        return blockwise_attention(q, k, v, causal=causal, scale=scale)
    if heads % sp != 0:
        raise ValueError(
            f"ulysses needs n_heads ({heads}) divisible by sp ({sp}); "
            f"use attention_impl='ring' for this shape")
    if k.shape[2] % sp != 0:
        # Grouped-query KV with too few kv-heads for the swap: repeat only
        # up to lcm(H_kv, sp) — the contiguous q-to-kv group alignment is
        # preserved across the swap (device j's q heads map onto exactly
        # the kv heads it receives), and the remaining repeat up to H
        # happens locally after the swap, not on the wire.
        import math

        target = math.lcm(k.shape[2], sp)
        k = _repeat_kv(k, target)
        v = _repeat_kv(v, target)

    # [B, S/sp, H, D] -> (split heads, concat seq) -> [B, S, H/sp, D]
    swap = functools.partial(jax.lax.all_to_all, axis_name=axis_name,
                             split_axis=2, concat_axis=1, tiled=True)
    q_full = swap(q)
    k_full = swap(k)
    v_full = swap(v)
    k_full = _repeat_kv(k_full, q_full.shape[2])
    v_full = _repeat_kv(v_full, q_full.shape[2])
    out = blockwise_attention(q_full, k_full, v_full, causal=causal,
                              scale=scale)
    # [B, S, H/sp, D] -> (split seq, concat heads) -> [B, S/sp, H, D]
    return jax.lax.all_to_all(out, axis_name=axis_name, split_axis=1,
                              concat_axis=2, tiled=True)


def ulysses_attention_sharded(q, k, v, mesh, *, axis_name: str = "sp",
                              causal: bool = True,
                              batch_axes=("dp", "fsdp"),
                              head_axis: str = "tp"):
    """Convenience wrapper: shard_map ulysses_attention over ``mesh``
    (mirror of ``ring_attention_sharded``)."""
    from jax.sharding import PartitionSpec as P

    from ray_tpu.parallel.mesh import shard_map_compat

    spec = P(batch_axes, axis_name, head_axis, None)
    fn = functools.partial(ulysses_attention, axis_name=axis_name,
                           causal=causal)
    wrapped = shard_map_compat(fn, mesh, (spec, spec, spec), spec)
    return wrapped(q, k, v)
