"""Normalization ops.

Computed in float32 regardless of input dtype (bf16-safe), cast back on the
way out — the standard TPU recipe: VPU work stays elementwise and fuses into
the surrounding matmuls under XLA, so no Pallas kernel is warranted here.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_norm(x: jax.Array, weight: jax.Array, *, eps: float = 1e-5,
             upcast: bool = True) -> jax.Array:
    """RMSNorm (Llama-family). weight shape [dim]."""
    dtype = x.dtype
    if upcast:
        x = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    x = x * jax.lax.rsqrt(var + eps)
    return (x * weight.astype(x.dtype)).astype(dtype)


def layer_norm(x: jax.Array, weight: jax.Array, bias: jax.Array | None = None,
               *, eps: float = 1e-5) -> jax.Array:
    """LayerNorm (GPT-2/ViT-family)."""
    dtype = x.dtype
    x = x.astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    x = (x - mean) * jax.lax.rsqrt(var + eps)
    x = x * weight.astype(x.dtype)
    if bias is not None:
        x = x + bias.astype(x.dtype)
    return x.astype(dtype)
