"""Rotary position embeddings (RoPE), Llama-3 style with NTK scaling hooks.

Frequencies are precomputed once per model (static shapes keep the table out
of the jit trace); application is pure elementwise VPU work that XLA fuses
into the attention projections.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def rope_frequencies(head_dim: int, max_seq_len: int, *,
                     theta: float = 500_000.0,
                     scaling_factor: Optional[float] = None) -> jax.Array:
    """[max_seq_len, head_dim//2] complex-free cos/sin basis angles."""
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2,
                                           dtype=jnp.float32) / head_dim))
    pos = jnp.arange(max_seq_len, dtype=jnp.float32)
    if scaling_factor is not None:
        pos = pos / scaling_factor
    return jnp.outer(pos, inv_freq)  # [S, D/2]


def apply_rope(x: jax.Array, angles: jax.Array,
               positions: Optional[jax.Array] = None) -> jax.Array:
    """Rotate q or k. x: [..., S, H, D]; angles: [max_S, D/2].

    positions: optional [.., S] int32 absolute positions (for sequence-
    parallel shards and decode steps); defaults to 0..S-1.
    """
    seq_len = x.shape[-3]
    if positions is None:
        ang = angles[:seq_len]                      # [S, D/2]
        ang = ang[None, :, None, :]                 # [1, S, 1, D/2]
    else:
        ang = angles[positions]                     # [..., S, D/2]
        ang = ang[..., :, None, :]                  # [..., S, 1, D/2]
    cos, sin = jnp.cos(ang), jnp.sin(ang)
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)
