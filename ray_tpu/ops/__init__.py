"""TPU-native neural-net ops: the hot kernels of the model layer.

The reference delegates these to torch/CUDA (vLLM, flash-attn); here they are
first-class: pure-JAX reference implementations everywhere, Pallas TPU
kernels on the MXU path, and ring/all-to-all sequence parallelism built on
``shard_map`` + ``ppermute`` (SURVEY.md §5.7 — absent in the reference, a
native requirement for this build).
"""

from ray_tpu.ops.norms import rms_norm, layer_norm
from ray_tpu.ops.rope import apply_rope, rope_frequencies
from ray_tpu.ops.attention import attention, flash_attention
from ray_tpu.ops.ring_attention import ring_attention

__all__ = [
    "rms_norm", "layer_norm", "apply_rope", "rope_frequencies",
    "attention", "flash_attention", "ring_attention",
]
