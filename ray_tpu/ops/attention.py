"""Attention: reference JAX implementation + Pallas TPU flash kernel.

Reference capability: the reference repo delegates attention to vLLM /
flash-attn CUDA kernels (outside its tree). Here it is in-tree and
TPU-native:

- ``attention``      — dispatcher; GQA-aware, causal, autodiff-friendly.
- ``flash_attention``— Pallas online-softmax kernel (HBM→VMEM tiled,
  MXU matmuls, O(S) memory). Forward kernel + recompute-based VJP.

Shapes follow the JAX convention [batch, seq, heads, head_dim].
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def axis_size(axis_name) -> int:
    """Static size of a bound mesh axis, across jax versions:
    ``jax.lax.axis_size`` only exists in newer releases, and on older
    ones ``jax.core.axis_frame`` returns either the size itself or a
    frame object carrying it."""
    fn = getattr(jax.lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    frame = jax.core.axis_frame(axis_name)
    return frame if isinstance(frame, int) else frame.size


def _repeat_kv(k: jax.Array, num_q_heads: int) -> jax.Array:
    """GQA: repeat kv heads to match q heads. [B,S,Hkv,D] -> [B,S,H,D]."""
    num_kv = k.shape[-2]
    if num_kv == num_q_heads:
        return k
    return jnp.repeat(k, num_q_heads // num_kv, axis=-2)


def reference_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                        causal: bool = True,
                        positions_q: Optional[jax.Array] = None,
                        positions_k: Optional[jax.Array] = None,
                        scale: Optional[float] = None) -> jax.Array:
    """Plain softmax attention in f32; XLA fuses this well on TPU for
    moderate sequence lengths and it is fully differentiable."""
    head_dim = q.shape[-1]
    scale = scale if scale is not None else head_dim ** -0.5
    k = _repeat_kv(k, q.shape[-2])
    v = _repeat_kv(v, q.shape[-2])
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        if positions_q is None:
            positions_q = jnp.arange(q.shape[1])
        if positions_k is None:
            positions_k = jnp.arange(k.shape[1])
        mask = positions_q[:, None] >= positions_k[None, :]
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)


def blockwise_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                        causal: bool = True, block_k: int = 512,
                        scale: Optional[float] = None) -> jax.Array:
    """Memory-efficient differentiable attention: online-softmax scan over
    key chunks with a rematerialized body, so both forward AND backward are
    O(S·block_k) memory instead of O(S²). This is the training path for
    long sequences (and the flash kernel's VJP)."""
    head_dim = q.shape[-1]
    scale = scale if scale is not None else head_dim ** -0.5
    k = _repeat_kv(k, q.shape[-2])
    v = _repeat_kv(v, q.shape[-2])
    seq_k = k.shape[1]
    bk = min(block_k, seq_k)
    if seq_k % bk != 0:  # pad keys; padding masked out below
        pad = bk - seq_k % bk
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
    nk = k.shape[1] // bk
    rows = jnp.arange(q.shape[1])
    batch, seq_q, heads, _ = q.shape

    # [nk, B, bk, H, D] chunks scanned as the leading axis.
    kc = k.reshape(batch, nk, bk, heads, head_dim).swapaxes(0, 1)
    vc = v.reshape(batch, nk, bk, heads, head_dim).swapaxes(0, 1)

    @jax.checkpoint
    def body(carry, chunk):
        acc, m, l = carry
        ki, kb, vb = chunk
        s = jnp.einsum("bqhd,bkhd->bhqk", q, kb,
                       preferred_element_type=jnp.float32) * scale
        cols = ki * bk + jnp.arange(bk)
        mask = cols[None, :] < seq_k
        if causal:
            mask = mask & (rows[:, None] >= cols[None, :])
        s = jnp.where(mask[None, None], s, NEG_INF)
        m_c = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_c)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        a = jnp.einsum("bhqk,bkhd->bqhd", p, vb.astype(jnp.float32),
                       preferred_element_type=jnp.float32)
        acc = acc * jnp.swapaxes(alpha, 1, 2) + a
        return (acc, m_new, l), None

    acc = jnp.zeros((batch, seq_q, heads, head_dim), jnp.float32)
    m = jnp.full((batch, heads, seq_q, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((batch, heads, seq_q, 1), jnp.float32)
    (acc, m, l), _ = jax.lax.scan(
        body, (acc, m, l), (jnp.arange(nk), kc, vc))
    l = jnp.where(l == 0.0, 1.0, l)
    return (acc / jnp.swapaxes(l, 1, 2)).astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas flash-attention forward kernel
# ---------------------------------------------------------------------------

def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *,
                      scale: float, block_q: int, block_k: int, causal: bool,
                      num_k_blocks: int, seq_k: int):
    import jax.experimental.pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # Fully-masked blocks (k strictly above the causal diagonal) are skipped.
    should_run = True
    if causal:
        should_run = ki * block_k < (qi + 1) * block_q

    @pl.when(should_run)
    def _compute():
        # feed the MXU native dtypes (bf16 in, f32 accumulate) — no
        # explicit f32 casts of the operands
        q = q_ref[0]                               # [bq, D]
        k = k_ref[0]                               # [bk, D]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale     # [bq, bk]
        cols = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = cols < seq_k  # tail block: don't attend to padding keys
        # Zero padded V rows: their p weights are exp(NEG_INF)≈0, but
        # 0 * <uninitialized> is NaN when the pad is NaN (interpret mode),
        # and garbage-dependent on hardware — make the product exact 0.
        kvalid = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_k, 1), 0) < seq_k
        v = jnp.where(kvalid, v, 0)
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            mask = mask & (rows >= cols)
        s = jnp.where(mask, s, NEG_INF)
        m_prev = m_ref[:, :1]                      # [bq, 1]
        l_prev = l_ref[:, :1]
        m_curr = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_curr)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ki == num_k_blocks - 1)
    def _finalize():
        l = l_ref[:, :1]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)


def _interpret_default() -> bool:
    """Pallas kernels only compile for TPU; elsewhere (CPU test meshes)
    run the SAME kernel under the Pallas interpreter so tests exercise the
    real kernel logic."""
    try:
        return jax.devices()[0].platform != "tpu"
    except Exception:  # pragma: no cover - no backend at all
        return True


def _compiler_params(**kw):
    """Pallas-TPU compiler params across the TPUCompilerParams ->
    CompilerParams rename; a clear error beats a NoneType call when a
    jax release exposes neither name."""
    from jax.experimental.pallas import tpu as pltpu
    cls = getattr(pltpu, "CompilerParams",
                  getattr(pltpu, "TPUCompilerParams", None))
    if cls is None:
        raise RuntimeError(
            f"jax {jax.__version__}: pallas.tpu exposes neither "
            f"CompilerParams nor TPUCompilerParams; flash attention "
            f"needs a supported jax release")
    return cls(**kw)


@functools.partial(jax.jit,
                   static_argnames=("causal", "block_q", "block_k",
                                    "interpret"))
def _flash_forward(q, k, v, *, causal: bool, block_q: int, block_k: int,
                   interpret: bool):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    batch, seq_q, num_heads, head_dim = q.shape
    seq_k = k.shape[1]
    num_kv = k.shape[2]
    group = num_heads // num_kv
    scale = head_dim ** -0.5

    bq = min(block_q, seq_q)
    bk = min(block_k, seq_k)
    nq = pl.cdiv(seq_q, bq)
    nk = pl.cdiv(seq_k, bk)

    # Layout [B*H, S, D]: one grid row per (batch, head) pair.
    qt = q.transpose(0, 2, 1, 3).reshape(batch * num_heads, seq_q, head_dim)
    kt = k.transpose(0, 2, 1, 3).reshape(batch * num_kv, seq_k, head_dim)
    vt = v.transpose(0, 2, 1, 3).reshape(batch * num_kv, seq_k, head_dim)

    def kv_index(bh, qi, ki):
        return (bh // num_heads) * num_kv + (bh % num_heads) // group, ki, 0

    out = pl.pallas_call(
        functools.partial(_flash_fwd_kernel, scale=scale, block_q=bq,
                          block_k=bk, causal=causal, num_k_blocks=nk,
                          seq_k=seq_k),
        grid=(batch * num_heads, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, head_dim), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, bk, head_dim), kv_index),
            pl.BlockSpec((1, bk, head_dim), kv_index),
        ],
        out_specs=pl.BlockSpec((1, bq, head_dim),
                               lambda bh, qi, ki: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((batch * num_heads, seq_q, head_dim),
                                       q.dtype),
        scratch_shapes=[
            pltpu.VMEM((bq, head_dim), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
        ],
        compiler_params=_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qt, kt, vt)
    return out.reshape(batch, num_heads, seq_q, head_dim).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = True, block_q: int = 128,
                    block_k: int = 128, interpret: bool | None = None):
    """Pallas TPU flash attention. O(S) memory forward; backward recomputes
    blockwise (remat scan), so training memory stays O(S·block) too."""
    if interpret is None:
        interpret = _interpret_default()
    return _flash_forward(q, k, v, causal=causal, block_q=block_q,
                          block_k=block_k, interpret=interpret)


def _flash_fwd_rule(q, k, v, causal, block_q, block_k, interpret):
    if interpret is None:
        interpret = _interpret_default()
    out = _flash_forward(q, k, v, causal=causal, block_q=block_q,
                         block_k=block_k, interpret=interpret)
    return out, (q, k, v)


def _flash_bwd_rule(causal, block_q, block_k, interpret, res, g):
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: blockwise_attention(q_, k_, v_, causal=causal),
        q, k, v)
    return vjp(g)


flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:  # pragma: no cover - no backend at all
        return False


def attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
              causal: bool = True,
              positions_q: Optional[jax.Array] = None,
              positions_k: Optional[jax.Array] = None,
              use_flash: Optional[bool] = None) -> jax.Array:
    """Dispatcher: Pallas flash kernel on TPU when shapes tile cleanly,
    reference otherwise. Explicit position vectors force the reference path
    (the kernel assumes contiguous 0..S-1 positions)."""
    if use_flash is None:
        use_flash = (_on_tpu() and positions_q is None and positions_k is None
                     and q.shape[-1] % 128 == 0 and q.shape[1] >= 128)
    if use_flash:
        return flash_attention(q, k, v, causal)
    return reference_attention(q, k, v, causal=causal,
                               positions_q=positions_q,
                               positions_k=positions_k)
