"""Ring attention: context/sequence parallelism over an ICI mesh axis.

SURVEY.md §5.7: the reference has NO sequence parallelism — this is native
new design. Each device on the ``sp`` axis holds a contiguous sequence chunk
of q/k/v. K/V chunks rotate around the ring via ``jax.lax.ppermute``
(neighbor exchange rides the shortest ICI links) while each device
accumulates online-softmax partial results for its local queries —
blockwise attention with O(S/sp) memory per device and compute/communication
overlap left to XLA's latency-hiding scheduler.

Usage: call inside ``shard_map`` (or via ``ring_attention_sharded`` which
wraps itself) with q/k/v already sharded on the sequence dim.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from ray_tpu.ops.attention import NEG_INF, _repeat_kv, axis_size


def _block_attn(q, k, v, q_offset, k_offset, scale, causal):
    """One blockwise step: returns (unnormalized acc [B,S,H,D] f32,
    row-max m, row-sum l with shapes [B,H,S,1])."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        rows = q_offset + jnp.arange(q.shape[1])
        cols = k_offset + jnp.arange(k.shape[1])
        mask = rows[:, None] >= cols[None, :]
        s = jnp.where(mask[None, None], s, NEG_INF)
    # NB: no stop_gradient on m — alpha/beta in the combine step also
    # differentiate through m and autodiff relies on the cancellation.
    m = jnp.max(s, axis=-1, keepdims=True)                    # [B,H,Sq,1]
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    acc = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32),
                     preferred_element_type=jnp.float32)
    return acc, m, l


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                   axis_name: str = "sp", causal: bool = True,
                   scale: Optional[float] = None) -> jax.Array:
    """Attention over a sequence sharded on ``axis_name``.

    Must be called inside shard_map/pjit-SPMD context where ``axis_name``
    is bound. q/k/v: per-device chunks [B, S_local, H|Hkv, D].
    """
    head_dim = q.shape[-1]
    scale = scale if scale is not None else head_dim ** -0.5
    k = _repeat_kv(k, q.shape[-2])
    v = _repeat_kv(v, q.shape[-2])

    sp = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    chunk = q.shape[1]
    q_offset = idx * chunk

    batch, _, heads, _ = q.shape

    def body(step, carry):
        acc, m, l, kc, vc = carry
        # The kv chunk currently held arrived from device (idx - step) % sp.
        k_offset = ((idx - step) % sp) * chunk
        a, m_c, l_c = _block_attn(q, kc, vc, q_offset, k_offset, scale,
                                  causal)
        m_new = jnp.maximum(m, m_c)
        alpha = jnp.exp(m - m_new)
        beta = jnp.exp(m_c - m_new)
        l = alpha * l + beta * l_c
        # acc is [B,S,H,D]; alpha/beta are [B,H,S,1] -> transpose to match.
        alpha_t = jnp.swapaxes(alpha, 1, 2)
        beta_t = jnp.swapaxes(beta, 1, 2)
        acc = acc * alpha_t + a * beta_t
        m = m_new
        # Rotate kv to the next ring neighbor (ICI nearest-neighbor).
        perm = [(i, (i + 1) % sp) for i in range(sp)]
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        return acc, m, l, kc, vc

    acc = jnp.zeros(q.shape[:3] + (head_dim,), jnp.float32)
    m = jnp.full((batch, heads, chunk, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((batch, heads, chunk, 1), jnp.float32)
    if sp == 1:
        acc, m, l, _, _ = body(0, (acc, m, l, k, v))
    else:
        acc, m, l, _, _ = jax.lax.fori_loop(
            0, sp, body, (acc, m, l, k, v))
    l = jnp.where(l == 0.0, 1.0, l)
    out = acc / jnp.swapaxes(l, 1, 2)
    return out.astype(q.dtype)


def ring_attention_sharded(q, k, v, mesh, *, axis_name: str = "sp",
                           causal: bool = True,
                           batch_axes=("dp", "fsdp"), head_axis: str = "tp"):
    """Convenience wrapper: shard_map ring_attention over ``mesh``."""
    from jax.sharding import PartitionSpec as P

    from ray_tpu.parallel.mesh import shard_map_compat

    spec = P(batch_axes, axis_name, head_axis, None)
    ring = functools.partial(ring_attention, axis_name=axis_name,
                             causal=causal)
    fn = shard_map_compat(ring, mesh, (spec, spec, spec), spec)
    return fn(q, k, v)
