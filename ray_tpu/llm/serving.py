"""LLM serving on ray_tpu.serve.

Reference: `python/ray/llm` — `build_openai_app` (`serve/builders/`),
`LLMConfig` (`serve/configs/server_models.py:159`), vLLM engine
deployments (`deployments/llm/vllm/vllm_models.py`). Here the engine is
the in-tree TPU continuous-batching engine; the deployment runs it on a
background thread and requests stream through per-request queues.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Dict, List, Optional

from ray_tpu import serve
from ray_tpu.llm.engine import (ContinuousBatchingEngine, SamplingParams)
from ray_tpu.llm.tokenizer import ByteTokenizer, load_tokenizer


@dataclasses.dataclass
class LLMConfig:
    model_id: str = "llama-debug"
    model_config: Optional[Any] = None       # LlamaConfig; debug if None
    tokenizer: Optional[str] = None          # None -> ByteTokenizer
    max_slots: int = 8
    max_seq: int = 512
    num_replicas: int = 1
    max_ongoing_requests: int = 64
    seed: int = 0
    # paged KV pool (reference: vLLM cache config surface,
    # `vllm_models.py:126-207`): block granularity and total pool size;
    # num_blocks=None sizes the pool to max_slots * max_seq
    block_size: Optional[int] = None   # None -> engine default (32)
    num_blocks: Optional[int] = None


class LLMServer:
    """Serve deployment class hosting one engine per replica."""

    def __init__(self, config: LLMConfig):
        import jax

        from ray_tpu.models.llama import LlamaConfig, LlamaModel

        self.config = config
        cfg = config.model_config or LlamaConfig.debug(
            vocab_size=512, max_seq_len=config.max_seq)
        self.model = LlamaModel(cfg)
        params = self.model.init(jax.random.key(config.seed))
        self.tokenizer = (load_tokenizer(config.tokenizer)
                          if config.tokenizer else ByteTokenizer())
        self.engine = ContinuousBatchingEngine(
            self.model, params, max_slots=config.max_slots,
            max_seq=config.max_seq, block_size=config.block_size,
            num_blocks=config.num_blocks)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self.engine.run_forever, args=(self._stop,), daemon=True)
        self._thread.start()

    def _parse(self, request: Dict[str, Any]):
        prompt = request.get("prompt", "")
        sampling = SamplingParams(
            max_tokens=int(request.get("max_tokens", 32)),
            temperature=float(request.get("temperature", 0.0)),
            top_k=int(request.get("top_k", 0)),
            stop_token_ids=(self.tokenizer.EOS,) if isinstance(
                self.tokenizer, ByteTokenizer) else ())
        ids = (prompt if isinstance(prompt, list)
               else self.tokenizer.encode(prompt))
        return ids, sampling

    def stream(self, request: Dict[str, Any]):
        """Streaming completions: one chunk per generated token as the
        engine produces it (reference: ray.llm streaming through Serve;
        the TTFT the serving bench measures is only real if the first
        token can leave the replica before generation completes)."""
        ids, sampling = self._parse(request)
        req = self.engine.submit(ids, sampling)
        index = 0
        for tok in req.iter_tokens():
            yield {"id": f"cmpl-{req.id}", "model": self.config.model_id,
                   "delta": self.tokenizer.decode([tok]),
                   "token_id": int(tok), "index": index}
            index += 1
        yield {"id": f"cmpl-{req.id}", "model": self.config.model_id,
               "finish_reason": req.finish_reason, "done": True,
               "usage": {"prompt_tokens": len(ids),
                         "completion_tokens": len(req.output)},
               "ttft_s": req.ttft_s}

    def __call__(self, request: Dict[str, Any]):
        """OpenAI-completions-shaped request/response; ``stream: true``
        returns a generator (chunk-per-token through Serve streaming)."""
        if isinstance(request, dict) and request.get("stream") is True:
            return self.stream(request)
        ids, sampling = self._parse(request)
        req = self.engine.submit(ids, sampling)
        req.done.wait(timeout=300)
        text = self.tokenizer.decode(req.output)
        return {
            "id": f"cmpl-{req.id}",
            "model": self.config.model_id,
            "text": text,
            "token_ids": list(req.output),
            "finish_reason": req.finish_reason,
            "usage": {"prompt_tokens": len(ids),
                      "completion_tokens": len(req.output)},
            "ttft_s": req.ttft_s,
        }

    def stats(self) -> Dict[str, Any]:
        return dict(self.engine.stats)

    def queue_depth(self) -> int:
        """Engine backlog beyond the decode slots: requests submitted
        but still waiting for admission. The serve replica reports this
        with its metrics push (serve/replica.py), so routers and the
        autoscaler see engine pressure, not just request counts."""
        return len(self.engine.waiting)

    def __del__(self):
        try:
            self._stop.set()
        except Exception:
            pass


def build_llm_app(config: LLMConfig) -> serve.Application:
    """`build_openai_app` equivalent: one autoscalable LLM deployment."""
    dep = serve.deployment(
        LLMServer, name=config.model_id,
        num_replicas=config.num_replicas,
        max_ongoing_requests=config.max_ongoing_requests)
    return dep.bind(config)
