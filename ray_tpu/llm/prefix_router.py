"""Prefix-aware request routing.

Reference: `python/ray/llm/_internal/serve/request_router/prefix_aware/`
(PrefixAwarePow2ReplicaRouter): requests whose prompts share a prefix go
to the same replica so its KV/prefix cache hits; cold prefixes fall back
to power-of-two-choices.
"""

from __future__ import annotations

import random
import threading
from typing import Dict, List, Optional, Sequence, Tuple


class PrefixTree:
    """Token-prefix → replica map with per-node hit accounting."""

    def __init__(self, block_size: int = 16, max_nodes: int = 100_000):
        self.block_size = block_size
        self.max_nodes = max_nodes
        self._map: Dict[Tuple, int] = {}
        self._lock = threading.Lock()

    def _blocks(self, tokens: Sequence[int]) -> List[Tuple]:
        out = []
        for i in range(self.block_size, len(tokens) + 1, self.block_size):
            out.append(tuple(tokens[:i]))
        return out

    def lookup(self, tokens: Sequence[int]) -> Tuple[Optional[int], int]:
        """Longest cached prefix → (replica, matched_len)."""
        best, matched = None, 0
        with self._lock:
            for block in self._blocks(tokens):
                replica = self._map.get(block)
                if replica is None:
                    break
                best, matched = replica, len(block)
        return best, matched

    def insert(self, tokens: Sequence[int], replica: int) -> None:
        with self._lock:
            if len(self._map) > self.max_nodes:
                self._map.clear()   # cheap global eviction
            for block in self._blocks(tokens):
                self._map[block] = replica


class PrefixAwareRouter:
    """Pick a replica index for a tokenized prompt."""

    def __init__(self, num_replicas: int, *, block_size: int = 16,
                 imbalance_limit: float = 2.0, seed: int = 0):
        self.num_replicas = num_replicas
        self.tree = PrefixTree(block_size=block_size)
        self.inflight = [0] * num_replicas
        self.imbalance_limit = imbalance_limit
        self._rng = random.Random(seed)
        self._lock = threading.Lock()

    def route(self, prompt_tokens: Sequence[int]) -> int:
        replica, matched = self.tree.lookup(prompt_tokens)
        with self._lock:
            mean = sum(self.inflight) / max(1, self.num_replicas)
            if (replica is not None
                    and self.inflight[replica] <= max(
                        self.imbalance_limit * mean, mean + 2)):
                chosen = replica           # prefix affinity wins
            elif self.num_replicas == 1:
                chosen = 0
            else:                           # cold prefix: P2C
                a, b = self._rng.sample(range(self.num_replicas), 2)
                chosen = a if self.inflight[a] <= self.inflight[b] else b
            self.inflight[chosen] += 1
        self.tree.insert(prompt_tokens, chosen)
        return chosen

    def on_finished(self, replica: int) -> None:
        with self._lock:
            self.inflight[replica] = max(0, self.inflight[replica] - 1)
