"""Tokenizers for the LLM stack.

ByteTokenizer: dependency-free byte-level tokenizer (ids = utf-8 bytes,
+BOS/EOS) used by tests and demos. ``load_tokenizer`` returns a
HuggingFace tokenizer when `transformers` has one cached locally
(reference: ray.llm resolves tokenizers through vLLM/HF).
"""

from __future__ import annotations

from typing import List, Optional


class ByteTokenizer:
    BOS = 256
    EOS = 257

    vocab_size = 258

    def encode(self, text: str, add_bos: bool = True) -> List[int]:
        ids = list(text.encode("utf-8"))
        return ([self.BOS] + ids) if add_bos else ids

    def decode(self, ids: List[int]) -> str:
        return bytes(i for i in ids if i < 256).decode("utf-8",
                                                       errors="replace")


def load_tokenizer(name_or_path: Optional[str] = None):
    if name_or_path is None:
        return ByteTokenizer()
    from transformers import AutoTokenizer
    return AutoTokenizer.from_pretrained(name_or_path)
