"""ray_tpu.llm — LLM serving + batch inference.

Reference: Ray LLM (`python/ray/llm`, SURVEY.md §2.2): vLLM-backed
deployments, TP/PP placement, prefix routing, batch inference. Here the
engine itself is in-tree and TPU-native (continuous batching over a
slot-major HBM KV cache; see engine.py).
"""

from ray_tpu.llm.engine import (ContinuousBatchingEngine, Request,
                                SamplingParams)
from ray_tpu.llm.serving import LLMConfig, LLMServer, build_llm_app
from ray_tpu.llm.tokenizer import ByteTokenizer, load_tokenizer

__all__ = [
    "ContinuousBatchingEngine", "SamplingParams", "Request",
    "LLMConfig", "LLMServer", "build_llm_app",
    "ByteTokenizer", "load_tokenizer",
]
