"""Host-side block pool for the paged KV cache.

Reference capability: vLLM's BlockSpaceManager / prefix caching (the
engine behind `ray.llm`, outside the reference tree; its TPU/HBM
config surface at `python/ray/llm/_internal/serve/deployments/llm/vllm/
vllm_models.py:126-207`). PAPERS.md: PagedAttention (Kwon et al.).

Design (vLLM-v1-shaped, TPU-adapted):

- The DEVICE side is one pool ``[L, num_blocks, block_size, Hkv, D]``
  per k/v (allocated once, scanned over L); THIS module is the host
  side: free-list, per-block refcounts, and the content-hash prefix
  index. No jax imports — pure Python, unit-testable anywhere.
- Blocks are IMMUTABLE once full. A prompt's full blocks are hashed by
  chain ``h_i = hash(h_{i-1}, tokens_i)``; identical prefixes across
  live requests resolve to the SAME physical blocks (refcount++), so
  admission skips both HBM and prefill FLOPs for the shared prefix.
  Writes only ever target a request's own tail blocks (a prefix hit is
  full-block-granular, so the write offset always lands in a private
  block) — classic copy-on-write never triggers without beam search,
  which keeps the device side scatter-free.
- Freed blocks go to an LRU free-list but KEEP their prefix-index entry
  (content stays valid in HBM) until the block is reallocated — a later
  request with the same prefix can resurrect a "free" block. This is
  the cross-request prefix cache; eviction is allocation itself.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

_NO_HASH = None


class BlockPool:
    """Refcounted physical blocks + content-hash prefix index."""

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 1 or block_size < 1:
            raise ValueError("num_blocks and block_size must be >= 1")
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.refcount = [0] * num_blocks
        # LRU order: oldest-freed first == evicted first
        self._free: "OrderedDict[int, None]" = OrderedDict(
            (i, None) for i in range(num_blocks))
        # content hash -> physical block (live or cached-free)
        self._by_hash: Dict[int, int] = {}
        self._hash_of: List[Optional[int]] = [_NO_HASH] * num_blocks
        self.stats = {"prefix_hits": 0, "prefix_queries": 0,
                      "evictions": 0}

    # -- introspection ----------------------------------------------------
    @property
    def num_free(self) -> int:
        return len(self._free)

    def cached_free_blocks(self) -> int:
        """Free blocks still carrying reusable prefix content."""
        return sum(1 for b in self._free if self._hash_of[b] is not None)

    # -- hashing ----------------------------------------------------------
    @staticmethod
    def chain_hashes(tokens: Sequence[int], block_size: int,
                     extra_key: Optional[Tuple] = None) -> List[int]:
        """Hash chain over the FULL blocks of ``tokens``. ``extra_key``
        (e.g. a model/adapter id) salts the chain so different models
        never share blocks.

        Content addressing uses sha256, not Python ``hash()``: a 64-bit
        hash collision (or a crafted token sequence in a multi-tenant
        server) would silently map different block contents onto the
        same physical block and serve wrong KV. The digest cost is
        negligible next to prefill FLOPs (vLLM made the same move)."""
        import hashlib

        hashes: List[int] = []
        prev = hashlib.sha256(repr(extra_key).encode()).digest()
        for start in range(0, len(tokens) - block_size + 1, block_size):
            h = hashlib.sha256(prev)
            h.update(repr(tuple(tokens[start:start + block_size]))
                     .encode())
            prev = h.digest()
            hashes.append(int.from_bytes(prev[:16], "little"))
        return hashes

    # -- allocation -------------------------------------------------------
    def match_prefix(self, hashes: Sequence[int]) -> List[int]:
        """Longest prefix of ``hashes`` resolvable to live-or-cached
        blocks. Returns the physical ids (NOT yet referenced)."""
        out: List[int] = []
        self.stats["prefix_queries"] += 1
        for h in hashes:
            b = self._by_hash.get(h)
            if b is None:
                break
            out.append(b)
        if out:
            self.stats["prefix_hits"] += 1
        return out

    def ref(self, block: int) -> None:
        """Take a reference; resurrects a cached-free block."""
        if self.refcount[block] == 0:
            self._free.pop(block, None)
        self.refcount[block] += 1

    def alloc(self, n: int) -> Optional[List[int]]:
        """Allocate ``n`` fresh (private, writable) blocks, or None if
        the pool can't cover it. Eviction = reusing the LRU free block,
        dropping whatever prefix content it still cached."""
        if n > len(self._free):
            return None
        out = []
        for _ in range(n):
            b, _ = self._free.popitem(last=False)
            old = self._hash_of[b]
            if old is not None:
                self._by_hash.pop(old, None)
                self._hash_of[b] = _NO_HASH
                self.stats["evictions"] += 1
            self.refcount[b] = 1
            out.append(b)
        return out

    def seal(self, block: int, content_hash: int) -> None:
        """Mark a full block's content, making it prefix-shareable. If
        an identical block is already indexed, the index keeps the OLD
        one (dedup happens at the next admission, not retroactively)."""
        if self._hash_of[block] is not None:
            return
        if content_hash in self._by_hash:
            return
        self._by_hash[content_hash] = block
        self._hash_of[block] = content_hash

    def unref(self, block: int) -> None:
        """Drop a reference; at zero the block joins the free list but
        keeps its prefix-index entry (cached-free) until reallocated."""
        if self.refcount[block] <= 0:
            raise ValueError(f"unref of unreferenced block {block}")
        self.refcount[block] -= 1
        if self.refcount[block] == 0:
            self._free[block] = None   # append = most-recently-freed

    def unref_all(self, blocks: Sequence[int]) -> None:
        for b in blocks:
            self.unref(b)


class SlotAllocation:
    """A slot's logical->physical block mapping plus which of its
    blocks were prefix hits (already containing K/V)."""

    __slots__ = ("blocks", "shared_blocks", "sealed_upto")

    def __init__(self, blocks: List[int], shared_blocks: int):
        self.blocks = blocks              # physical ids, logical order
        self.shared_blocks = shared_blocks
        self.sealed_upto = shared_blocks  # blocks already hash-indexed

    @property
    def capacity(self) -> int:
        return len(self.blocks)


def allocate_slot(pool: BlockPool, prompt: Sequence[int],
                  reserve_tokens: Optional[int] = None,
                  extra_key: Optional[Tuple] = None
                  ) -> Optional[Tuple[SlotAllocation, int]]:
    """Allocate blocks for a request: longest shared prefix from the
    pool's index + fresh blocks covering the rest of ``reserve_tokens``
    (default: the prompt). Decode-time growth goes through
    ``ensure_capacity``; exhaustion there triggers engine preemption.

    Returns (allocation, shared_token_count) or None if the pool cannot
    cover the non-shared remainder right now.
    """
    bs = pool.block_size
    reserve_tokens = max(reserve_tokens or 0, len(prompt))
    hashes = pool.chain_hashes(prompt, bs, extra_key)
    shared = pool.match_prefix(hashes)
    # never share the block holding the LAST prompt token: a FULL-prompt
    # hit would skip prefill entirely and the engine still needs the
    # last-token logits — keep >=1 token of real prefill.
    if len(shared) * bs >= len(prompt):
        shared = shared[:max(0, (len(prompt) - 1) // bs)]
    n_shared_tok = len(shared) * bs
    total_blocks = (reserve_tokens + bs - 1) // bs
    n_fresh = total_blocks - len(shared)
    # ref shared blocks FIRST: alloc() below may otherwise evict a
    # cached-free block that match_prefix just handed us
    for b in shared:
        pool.ref(b)
    fresh = pool.alloc(n_fresh)
    if fresh is None:
        pool.unref_all(shared)
        return None
    alloc = SlotAllocation(list(shared) + fresh, len(shared))
    return alloc, n_shared_tok


def ensure_capacity(pool: BlockPool, alloc: SlotAllocation,
                    needed_tokens: int) -> bool:
    """Grow ``alloc`` until it covers ``needed_tokens``. False = pool
    exhausted (caller preempts someone)."""
    bs = pool.block_size
    need = (needed_tokens + bs - 1) // bs - len(alloc.blocks)
    if need <= 0:
        return True
    fresh = pool.alloc(need)
    if fresh is None:
        return False
    alloc.blocks.extend(fresh)
    return True


def seal_prompt_blocks(pool: BlockPool, alloc: SlotAllocation,
                       prompt: Sequence[int],
                       extra_key: Optional[Tuple] = None) -> None:
    """After prefill lands, index the prompt's full blocks so later
    requests can share them."""
    bs = pool.block_size
    hashes = pool.chain_hashes(prompt, bs, extra_key)
    for i in range(alloc.sealed_upto, len(hashes)):
        pool.seal(alloc.blocks[i], hashes[i])
    alloc.sealed_upto = max(alloc.sealed_upto, len(hashes))
