"""TPU continuous-batching inference engine with a paged KV cache.

Reference capability: ray.llm serves via the vLLM engine (outside the
reference tree, `llm/_internal/serve/deployments/llm/vllm/`); this engine
is the in-tree TPU-native equivalent (BASELINE.md config 5):

- PAGED KV cache: one block pool ``[L, num_blocks, bs, Hkv, D]`` in HBM
  shared by all slots through per-slot block tables (PAPERS.md paged
  attention; `llm/paged_cache.py` owns the host-side pool), so HBM holds
  ragged sequences without per-slot max_seq reservations;
- PREFIX REUSE: full prompt blocks are content-hashed; identical
  prefixes across requests (and across time — freed blocks stay
  reusable until reallocated) share physical blocks AND skip their
  prefill FLOPs via a suffix-prefill that attends over the cached
  prefix (`LlamaModel.prefill_with_prefix`);
- requests admitted into free slots at any time (continuous batching —
  decode never drains to admit); pool exhaustion mid-decode PREEMPTS
  the youngest slot by recompute (blocks freed, request requeued with
  its generated tokens folded into the prompt), like vLLM's
  recompute-preemption;
- prefill at bucketed lengths (static shapes → one jit specialization
  per bucket, no recompilation churn), scattered into pool blocks;
- decode is ONE jitted step for all slots every iteration (inactive
  slots masked), block tables riding along as a tiny int32 array;
  sampling on-device, only B int32s return to host per step;
- per-request TTFT / throughput stats (the reference's
  `release/llm_tests/serve/benchmark/load_test.py` metrics).
"""

from __future__ import annotations

import dataclasses
import itertools
import queue
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.llm.paged_cache import (BlockPool, SlotAllocation,
                                     allocate_slot, ensure_capacity,
                                     seal_prompt_blocks)


@dataclasses.dataclass
class SamplingParams:
    max_tokens: int = 64
    temperature: float = 0.0
    top_k: int = 0                 # 0 = no top-k
    stop_token_ids: tuple = ()
    seed: int = 0


class Request:
    _ids = itertools.count()

    def __init__(self, prompt_tokens: List[int], sampling: SamplingParams):
        self.id = next(Request._ids)
        self.prompt = list(prompt_tokens)
        self.sampling = sampling
        self.output: List[int] = []
        self.stream: "queue.Queue" = queue.Queue()
        self.submitted_at = time.perf_counter()
        self.first_token_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        self.done = threading.Event()
        self.finish_reason: Optional[str] = None
        self.preemptions = 0

    @property
    def ttft_s(self) -> Optional[float]:
        if self.first_token_at is None:
            return None
        return self.first_token_at - self.submitted_at

    def cache_tokens(self) -> List[int]:
        """Tokens whose K/V must be cached before the next decode step —
        the prompt plus everything generated so far (non-empty output
        only after a preemption re-admission)."""
        return self.prompt + self.output

    def iter_tokens(self):
        """Stream tokens as they are generated."""
        while True:
            tok = self.stream.get()
            if tok is None:
                return
            yield tok


class ContinuousBatchingEngine:
    def __init__(self, model, params, *, max_slots: int = 32,
                 max_seq: int = 1024,
                 prefill_buckets: tuple = (32, 64, 128, 256, 512),
                 block_size: Optional[int] = None,
                 num_blocks: Optional[int] = None):
        self.model = model
        self.params = params
        self.max_slots = max_slots
        self.max_seq = max_seq
        self.buckets = tuple(b for b in sorted(prefill_buckets)
                             if b <= max_seq)
        if not self.buckets:
            raise ValueError(
                f"no prefill bucket fits max_seq={max_seq}: "
                f"{prefill_buckets}")
        requested = block_size
        block_size = requested if requested is not None else 32
        if self.buckets and block_size > self.buckets[0]:
            # prefill scatters whole buckets into blocks, so every
            # bucket must be block-aligned; shrink toward the smallest
            # bucket. LOUD only for an EXPLICIT request — a caller who
            # sized num_blocks for that granularity would otherwise get
            # half the KV pool silently (the default just adapts).
            if requested is not None:
                import warnings
                warnings.warn(
                    f"block_size={requested} exceeds the smallest "
                    f"prefill bucket {self.buckets[0]}; using "
                    f"{self.buckets[0]} — resize num_blocks "
                    f"accordingly", stacklevel=2)
            block_size = self.buckets[0]
        for b in self.buckets:
            if b % block_size != 0:
                raise ValueError(
                    f"prefill bucket {b} not a multiple of "
                    f"block_size {block_size}")
        self.block_size = block_size
        self.blocks_per_slot = (max_seq + block_size - 1) // block_size
        if num_blocks is None:
            num_blocks = max_slots * self.blocks_per_slot
        self.num_blocks = num_blocks
        self.pool = BlockPool(num_blocks, block_size)
        # +1: physical block ``num_blocks`` is the SCRATCH block — every
        # padded table/scatter entry points there, so inactive slots and
        # bucket padding write garbage into scratch instead of a live
        # block, and every device index stays in-bounds (no OOB DMA for
        # the Pallas path to trip on)
        self.kv = model.init_kv_pool(num_blocks + 1, block_size)

        self.slots: List[Optional[Request]] = [None] * max_slots
        self.allocs: List[Optional[SlotAllocation]] = [None] * max_slots
        self.offsets = np.zeros(max_slots, np.int32)   # tokens cached/slot
        self._tables = np.full((max_slots, self.blocks_per_slot),
                               num_blocks, np.int32)
        self._admit_order: List[int] = []   # oldest-first slot ids
        self.waiting: "deque[Request]" = deque()
        self._lock = threading.Lock()
        self._rng_key = jax.random.key(0)

        # jitted programs ------------------------------------------------
        self._decode = jax.jit(model.decode_step_paged,
                               donate_argnums=(2,))
        self._prefill = jax.jit(self._prefill_impl)
        self._prefill_prefix = jax.jit(model.prefill_with_prefix)
        self._insert = jax.jit(self._insert_impl, donate_argnums=(0,))
        self._gather = jax.jit(self._gather_impl)
        self._sample = jax.jit(self._sample_impl)

        self.stats = {"requests": 0, "tokens_generated": 0,
                      "decode_steps": 0, "prefills": 0,
                      "prefix_prefills": 0, "prefix_tokens_reused": 0,
                      "preemptions": 0}

    # -- jitted internals --------------------------------------------------
    def _prefill_impl(self, params, tokens, lengths):
        """BATCHED prefill: tokens [N, Tb], lengths [N]; returns each
        request's last-valid-token logits [N, V] + a BUCKET-SIZED cache
        [L, N, Tb, Hkv, D] that admission scatters into pool blocks."""
        N, Tb = tokens.shape
        small = self.model.init_kv_cache(N, Tb)
        logits, small = self.model.forward_step(
            params, tokens, small, jnp.zeros((N,), jnp.int32))
        last = jnp.take_along_axis(
            logits, (lengths - 1)[:, None, None], axis=1)[:, 0]
        return last, small

    def _insert_impl(self, pool, small, block_ids):
        """Scatter bucket prefill K/V [L, N, Tb, Hkv, D] into pool
        blocks. ``block_ids`` [N*nb] flat physical ids in logical order
        (pad with num_blocks = the scratch block)."""
        L, N, Tb = small["k"].shape[:3]
        bs = self.block_size
        nb = Tb // bs

        def to_blocks(x):
            # [L, N, Tb, H, D] -> [L, N*nb, bs, H, D]
            return x.reshape(L, N * nb, bs, *x.shape[3:])

        k = pool["k"].at[:, block_ids].set(to_blocks(small["k"]))
        v = pool["v"].at[:, block_ids].set(to_blocks(small["v"]))
        return {"k": k, "v": v}

    def _gather_impl(self, pool, block_ids):
        """Gather prefix blocks [N, Pb] -> dense [L, N, Pb*bs, Hkv, D]."""
        k = pool["k"][:, block_ids]          # [L, N, Pb, bs, Hkv, D]
        v = pool["v"][:, block_ids]
        L, N, Pb, bs = k.shape[:4]
        return (k.reshape(L, N, Pb * bs, *k.shape[4:]),
                v.reshape(L, N, Pb * bs, *v.shape[4:]))

    def _sample_impl(self, logits, temps, top_ks, key):
        """logits [B, V] → tokens [B] on-device."""
        B, V = logits.shape
        keys = jax.random.split(key, B)
        greedy = jnp.argmax(logits, axis=-1)

        def sample_row(lg, temp, tk, k):
            scaled = lg / jnp.maximum(temp, 1e-6)
            # top-k masking with static k = full V (mask below threshold)
            def apply_topk(s):
                kth = jnp.sort(s)[V - jnp.maximum(tk, 1)]
                return jnp.where(s >= kth, s, -1e30)
            scaled = jax.lax.cond(tk > 0, apply_topk, lambda s: s, scaled)
            return jax.random.categorical(k, scaled)

        sampled = jax.vmap(sample_row)(logits, temps, top_ks, keys)
        return jnp.where(temps <= 0.0, greedy, sampled)

    # -- public API --------------------------------------------------------
    def submit(self, prompt_tokens: List[int],
               sampling: Optional[SamplingParams] = None) -> Request:
        req = Request(prompt_tokens, sampling or SamplingParams())
        self.stats["requests"] += 1
        # deque.append is atomic — submitters never contend on the
        # engine-step lock (a step can span a whole prefill+decode)
        self.waiting.append(req)
        return req

    def has_work(self) -> bool:
        return (bool(self.waiting)
                or any(s is not None for s in self.slots))

    def step(self) -> int:
        """One engine iteration: admit+prefill, then one decode step for
        all active slots. Returns number of active slots."""
        with self._lock:
            self._admit()
            return self._decode_step()

    def _bucket_for(self, n: int) -> Optional[int]:
        for b in self.buckets:
            if n <= b:
                return b
        return None

    # -- admission ---------------------------------------------------------
    def _admit(self) -> None:
        """Admit as many waiting requests as slots AND pool blocks
        allow. Prefix-hit requests prefill one-by-one through the
        suffix path; the rest batch per bucket (one forward per
        bucket). Pool exhaustion stops admission (FIFO order held)."""
        free = [i for i, r in enumerate(self.slots) if r is None]
        if not free or not self.waiting:
            return
        by_bucket: Dict[int, List] = {}
        chunked_group: List = []
        while free and self.waiting:
            req = self.waiting.popleft()
            toks = req.cache_tokens()
            n = len(toks)
            never_fits = ((n + 1 + self.block_size - 1)
                          // self.block_size > self.num_blocks)
            if n >= self.max_seq or never_fits:
                req.finish_reason = ("length" if req.output
                                     else "prompt_too_long")
                req.finished_at = time.perf_counter()
                req.done.set()
                req.stream.put(None)
                continue
            # +1 so the first decode write never needs a growth step
            alloc = allocate_slot(self.pool, toks, n + 1)
            if alloc is None:
                # pool can't host it right now — put it back, stop
                self.waiting.appendleft(req)
                break
            alloc, shared_tok = alloc
            slot = free.pop(0)
            bucket = self._bucket_for(n)
            if shared_tok > 0 or bucket is None:
                # prefix hit, or context longer than the largest
                # bucket (e.g. a preempted request's regrown context):
                # CHUNKED prefill over the cached/growing prefix
                chunked_group.append((slot, req, alloc, shared_tok))
            else:
                by_bucket.setdefault(bucket, []).append(
                    (slot, req, alloc))
        # single-chunk prefix hits with identical padded shapes BATCH
        # through prefill_with_prefix's N dimension (the common wave of
        # same-prefix requests); multi-chunk contexts go one-by-one
        big = self.buckets[-1]
        by_shape: Dict[tuple, List] = {}
        singles: List = []
        for item in chunked_group:
            _, req, alloc, shared_tok = item
            suffix_len = len(req.cache_tokens()) - shared_tok
            if 0 < shared_tok and suffix_len <= big:
                pb_pad = self._pad_pow2(
                    max(shared_tok // self.block_size, 1),
                    self.blocks_per_slot)
                key = (pb_pad, self._bucket_for(suffix_len))
                by_shape.setdefault(key, []).append(item)
            else:
                singles.append(item)
        for (pb_pad, s_bucket), group in by_shape.items():
            self._admit_prefix_batch(pb_pad, s_bucket, group)
        for slot, req, alloc, shared_tok in singles:
            self._admit_chunked(slot, req, alloc, shared_tok)
        for bucket, group in by_bucket.items():
            self._admit_bucket(bucket, group)

    def _pad_pow2(self, n: int, cap: int) -> int:
        p = 1
        while p < n:
            p *= 2
        return min(p, cap)

    def _admit_bucket(self, bucket: int, group: List) -> None:
        """Batched no-prefix prefill: one forward + one pool scatter +
        one sample for the whole group."""
        bs = self.block_size
        nb = bucket // bs
        # pad the group to the next power of two so each bucket has
        # O(log max_slots) jit specializations, not one per N
        n_pad = self._pad_pow2(len(group), self.max_slots)
        lengths = np.ones(n_pad, np.int32)
        toks = np.zeros((n_pad, bucket), np.int32)
        block_ids = np.full(n_pad * nb, self.num_blocks, np.int32)
        for row, (slot, req, alloc) in enumerate(group):
            seq = req.cache_tokens()
            lengths[row] = len(seq)
            toks[row, :len(seq)] = seq
            ids = alloc.blocks[:nb]
            block_ids[row * nb:row * nb + len(ids)] = ids
        last_logits, small = self._prefill(
            self.params, jnp.asarray(toks), jnp.asarray(lengths))
        self.kv = self._insert(self.kv, small, jnp.asarray(block_ids))
        self.stats["prefills"] += 1
        toks_out = self._sample_batch(last_logits,
                                      [req for _, req, _ in group], n_pad)
        now = time.perf_counter()
        for row, (slot, req, alloc) in enumerate(group):
            self._activate(slot, req, alloc, int(lengths[row]), now)
            self._emit(slot, int(toks_out[row]))

    def _admit_prefix_batch(self, pb_pad: int, s_bucket: int,
                            group: List) -> None:
        """Batched suffix prefill for same-shape prefix hits: one
        gather + one forward + one scatter + one sample for the wave."""
        bs = self.block_size
        nb = s_bucket // bs
        n_pad = self._pad_pow2(len(group), self.max_slots)
        ids = np.zeros((n_pad, pb_pad), np.int32)
        toks = np.zeros((n_pad, s_bucket), np.int32)
        plens = np.zeros(n_pad, np.int32)
        slens = np.ones(n_pad, np.int32)
        block_ids = np.full(n_pad * nb, self.num_blocks, np.int32)
        for row, (slot, req, alloc, shared) in enumerate(group):
            seq = req.cache_tokens()
            pb = shared // bs
            ids[row, :pb] = alloc.blocks[:pb]
            suffix = seq[shared:]
            toks[row, :len(suffix)] = suffix
            plens[row] = shared
            slens[row] = len(suffix)
            avail = alloc.blocks[pb:pb + nb]
            block_ids[row * nb:row * nb + len(avail)] = avail
            self.stats["prefix_prefills"] += 1
            self.stats["prefix_tokens_reused"] += shared
        pk, pv = self._gather(self.kv, jnp.asarray(ids))
        last_logits, small = self._prefill_prefix(
            self.params, jnp.asarray(toks), pk, pv,
            jnp.asarray(plens), jnp.asarray(slens))
        self.kv = self._insert(self.kv, small, jnp.asarray(block_ids))
        self.stats["prefills"] += 1
        toks_out = self._sample_batch(last_logits,
                                      [req for _, req, _, _ in group],
                                      n_pad)
        now = time.perf_counter()
        for row, (slot, req, alloc, shared) in enumerate(group):
            self._activate(slot, req, alloc, len(req.cache_tokens()), now)
            self._emit(slot, int(toks_out[row]))

    def _prefill_chunk(self, alloc: SlotAllocation, seq: List[int],
                       pos: int, chunk_len: int):
        """Prefill ``seq[pos:pos+chunk_len]`` attending over the
        already-cached ``pos`` tokens (gathered dense from the pool),
        scattering the chunk's K/V into the slot's blocks. ``pos`` is
        block-aligned. Returns the chunk's last-token logits."""
        bs = self.block_size
        pb = pos // bs
        chunk = seq[pos:pos + chunk_len]
        s_bucket = self._bucket_for(len(chunk))
        # pad the gathered prefix to a power-of-two block count to bound
        # jit specializations; padded rows are position-masked
        pb_pad = self._pad_pow2(max(pb, 1), self.blocks_per_slot)
        ids = np.zeros((1, pb_pad), np.int32)
        ids[0, :pb] = alloc.blocks[:pb]
        pk, pv = self._gather(self.kv, jnp.asarray(ids))
        toks = np.zeros((1, s_bucket), np.int32)
        toks[0, :len(chunk)] = chunk
        last_logits, small = self._prefill_prefix(
            self.params, jnp.asarray(toks), pk, pv,
            jnp.asarray([pos], np.int32),
            jnp.asarray([len(chunk)], np.int32))
        nb = s_bucket // bs
        block_ids = np.full(nb, self.num_blocks, np.int32)
        avail = alloc.blocks[pb:pb + nb]
        block_ids[:len(avail)] = avail
        # chunk cache is [L, 1, Tb, ...]: reuse the batched scatter
        self.kv = self._insert(self.kv, small, jnp.asarray(block_ids))
        self.stats["prefills"] += 1
        return last_logits

    def _admit_chunked(self, slot: int, req: Request,
                      alloc: SlotAllocation, shared_tok: int) -> None:
        """Single-request chunked prefill: the cached prefix (shared
        blocks and/or earlier chunks) is attended as context, so any
        context length admits — shared-prefix FLOPs are skipped, and a
        context longer than the largest bucket prefills in bucket-sized
        chunks (vLLM's chunked prefill)."""
        seq = req.cache_tokens()
        n = len(seq)
        if shared_tok > 0:
            self.stats["prefix_prefills"] += 1
            self.stats["prefix_tokens_reused"] += shared_tok
        pos = shared_tok
        big = self.buckets[-1]
        last_logits = None
        while pos < n:
            chunk_len = min(big, n - pos)
            last_logits = self._prefill_chunk(alloc, seq, pos, chunk_len)
            pos += chunk_len
        toks_out = self._sample_batch(last_logits, [req], 1)
        self._activate(slot, req, alloc, n, time.perf_counter())
        self._emit(slot, int(toks_out[0]))

    def _activate(self, slot: int, req: Request, alloc: SlotAllocation,
                  n_cached: int, now: float) -> None:
        seal_prompt_blocks(self.pool, alloc, req.cache_tokens())
        if req.first_token_at is None:
            req.first_token_at = now
        self.slots[slot] = req
        self.allocs[slot] = alloc
        self.offsets[slot] = n_cached
        self._tables[slot] = self.num_blocks
        self._tables[slot, :len(alloc.blocks)] = alloc.blocks
        self._admit_order.append(slot)

    def _sample_batch(self, logits, reqs: List[Request], n_pad: int):
        self._rng_key, sub = jax.random.split(self._rng_key)
        temps = np.zeros(n_pad, np.float32)
        top_ks = np.zeros(n_pad, np.int32)
        for row, req in enumerate(reqs):
            temps[row] = req.sampling.temperature
            top_ks[row] = req.sampling.top_k
        return np.asarray(self._sample(
            logits, jnp.asarray(temps), jnp.asarray(top_ks), sub))

    # -- decode ------------------------------------------------------------
    def _preempt(self, slot: int) -> None:
        """Free a slot's blocks and requeue its request (recompute
        preemption): generated tokens fold into the prompt so the
        re-admission prefill rebuilds the full context."""
        req = self.slots[slot]
        self.pool.unref_all(self.allocs[slot].blocks)
        self.slots[slot] = None
        self.allocs[slot] = None
        self.offsets[slot] = 0
        self._tables[slot] = self.num_blocks   # idle writes go to scratch
        self._admit_order.remove(slot)
        req.preemptions += 1
        self.stats["preemptions"] += 1
        self.waiting.appendleft(req)

    def _grow_or_preempt(self) -> None:
        """Every active slot must have capacity for its next token's
        K/V before the batched decode runs. Exhaustion preempts the
        YOUNGEST slot (recompute is cheapest for it) until the older
        ones fit — the victim may be the grower itself."""
        for slot in list(self._admit_order):      # oldest first
            if self.slots[slot] is None:
                continue
            alloc = self.allocs[slot]
            while not ensure_capacity(self.pool, alloc,
                                      int(self.offsets[slot]) + 1):
                # chunked prefill re-admits ANY context length, so plain
                # youngest-first is always safe (and discards the least
                # computed work)
                victims = [s for s in self._admit_order
                           if s != slot] or [slot]
                victim = victims[-1]
                self._preempt(victim)
                if victim == slot:
                    break
            if self.slots[slot] is not None:
                self._tables[slot, :len(alloc.blocks)] = alloc.blocks

    def _decode_step(self) -> int:
        self._grow_or_preempt()
        active = [i for i, r in enumerate(self.slots) if r is not None]
        if not active:
            return 0
        last_tokens = np.zeros(self.max_slots, np.int32)
        temps = np.zeros(self.max_slots, np.float32)
        top_ks = np.zeros(self.max_slots, np.int32)
        for i in active:
            req = self.slots[i]
            last_tokens[i] = req.output[-1] if req.output else \
                (req.prompt[-1] if req.prompt else 0)
            temps[i] = req.sampling.temperature
            top_ks[i] = req.sampling.top_k
        logits, self.kv = self._decode(
            self.params, jnp.asarray(last_tokens), self.kv,
            jnp.asarray(self._tables), jnp.asarray(self.offsets))
        self._rng_key, sub = jax.random.split(self._rng_key)
        toks = np.asarray(self._sample(
            logits, jnp.asarray(temps), jnp.asarray(top_ks), sub))
        self.stats["decode_steps"] += 1
        for i in active:
            self.offsets[i] += 1
            self._emit(i, int(toks[i]))
        return len(active)

    def _emit(self, slot: int, tok: int) -> None:
        req = self.slots[slot]
        req.output.append(tok)
        req.stream.put(tok)
        self.stats["tokens_generated"] += 1
        stop = (tok in req.sampling.stop_token_ids
                or len(req.output) >= req.sampling.max_tokens
                or self.offsets[slot] + 1 >= self.max_seq)
        if stop:
            req.finish_reason = ("stop" if tok in req.sampling.stop_token_ids
                                 else "length")
            req.finished_at = time.perf_counter()
            req.stream.put(None)
            req.done.set()
            # blocks go cached-free: content stays prefix-reusable
            # until the pool reallocates them
            self.pool.unref_all(self.allocs[slot].blocks)
            self.slots[slot] = None
            self.allocs[slot] = None
            self.offsets[slot] = 0
            self._tables[slot] = self.num_blocks   # idle writes → scratch
            self._admit_order.remove(slot)

    # -- prefill/decode disaggregation handoff -----------------------------
    def prefill_only(self, prompt_tokens: List[int]):
        """Prefill WITHOUT occupying a decode slot: returns
        (kv_small_numpy, last_logits_numpy, prompt_len) for transfer to a
        decode engine (reference: ray.llm prefill/decode disaggregation,
        `deployments/prefill_decode_disagg/`)."""
        n = len(prompt_tokens)
        bucket = self._bucket_for(n)
        if bucket is None:
            raise ValueError(f"prompt of {n} tokens exceeds buckets")
        toks = np.zeros((1, bucket), np.int32)
        toks[0, :n] = prompt_tokens
        last_logits, small = self._prefill(
            self.params, jnp.asarray(toks), jnp.asarray([n], np.int32))
        kv = {"k": np.asarray(small["k"]), "v": np.asarray(small["v"])}
        self.stats["prefills"] += 1
        return kv, np.asarray(last_logits[0]), n

    def submit_prefilled(self, prompt_tokens: List[int], kv: Dict,
                         last_logits, sampling: Optional[SamplingParams]
                         = None) -> Optional[Request]:
        """Admit a request whose prefill happened elsewhere. Returns None
        if no slot (or pool room) is free (caller retries)."""
        req = Request(prompt_tokens, sampling or SamplingParams())
        n = len(prompt_tokens)
        if n >= self.max_seq:
            req.finish_reason = "prompt_too_long"
            req.finished_at = time.perf_counter()
            req.done.set()
            req.stream.put(None)
            return req
        with self._lock:
            free = [i for i, s in enumerate(self.slots) if s is None]
            if not free:
                return None
            bs = self.block_size
            Tb = kv["k"].shape[2]
            nb = Tb // bs
            need = max((n + 1 + bs - 1) // bs, 1)
            blocks = self.pool.alloc(max(need, 0))
            if blocks is None:
                return None
            alloc = SlotAllocation(blocks, 0)
            block_ids = np.full(nb, self.num_blocks, np.int32)
            avail = blocks[:nb]
            block_ids[:len(avail)] = avail
            small = {"k": jnp.asarray(kv["k"]), "v": jnp.asarray(kv["v"])}
            self.kv = self._insert(self.kv, small,
                                   jnp.asarray(block_ids))
            slot = free[0]
            toks_out = self._sample_batch(jnp.asarray(last_logits)[None],
                                          [req], 1)
            self.stats["requests"] += 1
            self._activate(slot, req, alloc, n, time.perf_counter())
            self._emit(slot, int(toks_out[0]))
        return req

    # -- convenience -------------------------------------------------------
    def generate(self, prompts: List[List[int]],
                 sampling: Optional[SamplingParams] = None
                 ) -> List[Request]:
        reqs = [self.submit(p, sampling) for p in prompts]
        while self.has_work():
            self.step()
        return reqs

    def run_forever(self, stop_event: threading.Event,
                    idle_sleep_s: float = 0.002) -> None:
        """Background engine loop (used by the serving integration)."""
        while not stop_event.is_set():
            if self.step() == 0 and not self.waiting:
                time.sleep(idle_sleep_s)
