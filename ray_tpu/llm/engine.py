"""TPU continuous-batching inference engine.

Reference capability: ray.llm serves via the vLLM engine (outside the
reference tree, `llm/_internal/serve/deployments/llm/vllm/`); this engine
is the in-tree TPU-native equivalent (BASELINE.md config 5):

- slot-major KV cache [L, max_slots, max_seq, Hkv, D] resident in HBM;
- requests admitted into free slots at any time (continuous batching —
  decode never drains to admit);
- prefill at bucketed lengths (static shapes → one jit specialization per
  bucket, no recompation churn), scattered into the slot cache;
- decode is ONE jitted step for all slots every iteration (inactive slots
  masked), sampling on-device (greedy/temperature/top-k), only B int32s
  return to host per step;
- per-request TTFT / throughput stats (the reference's
  `release/llm_tests/serve/benchmark/load_test.py` metrics).
"""

from __future__ import annotations

import dataclasses
import itertools
import queue
import threading
import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass
class SamplingParams:
    max_tokens: int = 64
    temperature: float = 0.0
    top_k: int = 0                 # 0 = no top-k
    stop_token_ids: tuple = ()
    seed: int = 0


class Request:
    _ids = itertools.count()

    def __init__(self, prompt_tokens: List[int], sampling: SamplingParams):
        self.id = next(Request._ids)
        self.prompt = list(prompt_tokens)
        self.sampling = sampling
        self.output: List[int] = []
        self.stream: "queue.Queue" = queue.Queue()
        self.submitted_at = time.perf_counter()
        self.first_token_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        self.done = threading.Event()
        self.finish_reason: Optional[str] = None

    @property
    def ttft_s(self) -> Optional[float]:
        if self.first_token_at is None:
            return None
        return self.first_token_at - self.submitted_at

    def iter_tokens(self):
        """Stream tokens as they are generated."""
        while True:
            tok = self.stream.get()
            if tok is None:
                return
            yield tok


class ContinuousBatchingEngine:
    def __init__(self, model, params, *, max_slots: int = 8,
                 max_seq: int = 1024,
                 prefill_buckets: tuple = (32, 64, 128, 256, 512)):
        self.model = model
        self.params = params
        self.max_slots = max_slots
        self.max_seq = max_seq
        self.buckets = tuple(b for b in sorted(prefill_buckets)
                             if b <= max_seq)
        self.cache = model.init_kv_cache(max_slots, max_seq)

        self.slots: List[Optional[Request]] = [None] * max_slots
        self.offsets = np.zeros(max_slots, np.int32)   # tokens cached/slot
        self.waiting: "queue.Queue[Request]" = queue.Queue()
        self._lock = threading.Lock()
        self._rng_key = jax.random.key(0)

        # jitted programs ------------------------------------------------
        self._decode = jax.jit(self._decode_impl, donate_argnums=(1,))
        self._prefill = jax.jit(self._prefill_impl)
        self._insert = jax.jit(self._insert_impl, donate_argnums=(0,))
        self._sample = jax.jit(self._sample_impl)

        self.stats = {"requests": 0, "tokens_generated": 0,
                      "decode_steps": 0, "prefills": 0}

    # -- jitted internals --------------------------------------------------
    def _decode_impl(self, params, cache, tokens, offsets):
        logits, cache = self.model.forward_step(
            params, tokens[:, None], cache, offsets)
        return logits[:, 0], cache

    def _prefill_impl(self, params, tokens, lengths):
        """BATCHED prefill: tokens [N, Tb], lengths [N]; returns each
        request's last-valid-token logits [N, V] + a BUCKET-SIZED cache
        [L, N, Tb, Hkv, D] (never max_seq — admission writes only the
        bucket rows)."""
        N, Tb = tokens.shape
        small = self.model.init_kv_cache(N, Tb)
        logits, small = self.model.forward_step(
            params, tokens, small, jnp.zeros((N,), jnp.int32))
        last = jnp.take_along_axis(
            logits, (lengths - 1)[:, None, None], axis=1)[:, 0]
        return last, small

    def _insert_impl(self, cache, small, slots):
        """Scatter a bucket-sized prefill cache [L, N, Tb, ...] into the
        slot cache [L, max_slots, max_seq, ...] at ``slots`` [N] — a
        per-slot dynamic update of Tb rows, NOT a rebuild of max_seq."""
        Tb = small["k"].shape[2]
        k = cache["k"].at[:, slots, :Tb].set(small["k"])
        v = cache["v"].at[:, slots, :Tb].set(small["v"])
        return {"k": k, "v": v}

    def _sample_impl(self, logits, temps, top_ks, key):
        """logits [B, V] → tokens [B] on-device."""
        B, V = logits.shape
        keys = jax.random.split(key, B)
        greedy = jnp.argmax(logits, axis=-1)

        def sample_row(lg, temp, tk, k):
            scaled = lg / jnp.maximum(temp, 1e-6)
            # top-k masking with static k = full V (mask below threshold)
            def apply_topk(s):
                kth = jnp.sort(s)[V - jnp.maximum(tk, 1)]
                return jnp.where(s >= kth, s, -1e30)
            scaled = jax.lax.cond(tk > 0, apply_topk, lambda s: s, scaled)
            return jax.random.categorical(k, scaled)

        sampled = jax.vmap(sample_row)(logits, temps, top_ks, keys)
        return jnp.where(temps <= 0.0, greedy, sampled)

    # -- public API --------------------------------------------------------
    def submit(self, prompt_tokens: List[int],
               sampling: Optional[SamplingParams] = None) -> Request:
        req = Request(prompt_tokens, sampling or SamplingParams())
        self.stats["requests"] += 1
        self.waiting.put(req)
        return req

    def has_work(self) -> bool:
        return (not self.waiting.empty()
                or any(s is not None for s in self.slots))

    def step(self) -> int:
        """One engine iteration: admit+prefill, then one decode step for
        all active slots. Returns number of active slots."""
        with self._lock:
            self._admit()
            return self._decode_step()

    def _bucket_for(self, n: int) -> Optional[int]:
        for b in self.buckets:
            if n <= b:
                return b
        return None

    def _admit(self) -> None:
        """Admit as many waiting requests as there are free slots. All
        admissions sharing a bucket prefill in ONE batched forward (the
        reference engine's batched prefill), then one batched scatter
        into the slot cache and one batched sample."""
        free = [i for i, r in enumerate(self.slots) if r is None]
        if not free:
            return
        by_bucket: Dict[int, List] = {}
        while free:
            try:
                req = self.waiting.get_nowait()
            except queue.Empty:
                break
            n = len(req.prompt)
            bucket = self._bucket_for(n)
            if bucket is None or n >= self.max_seq:
                req.finish_reason = "prompt_too_long"
                req.done.set()
                req.stream.put(None)
                continue
            by_bucket.setdefault(bucket, []).append((free.pop(0), req))
        for bucket, group in by_bucket.items():
            # pad the group to the next power of two so each bucket has
            # O(log max_slots) jit specializations, not one per N (a
            # fresh XLA compile on the admission hot path would stall
            # every in-flight decode); padded slot ids point past
            # max_slots, which jax scatter DROPS.
            n_pad = 1
            while n_pad < len(group):
                n_pad *= 2
            n_pad = min(n_pad, self.max_slots)
            slots = np.full(n_pad, self.max_slots, np.int32)
            lengths = np.ones(n_pad, np.int32)
            toks = np.zeros((n_pad, bucket), np.int32)
            for row, (slot, req) in enumerate(group):
                slots[row] = slot
                lengths[row] = len(req.prompt)
                toks[row, :len(req.prompt)] = req.prompt
            last_logits, small = self._prefill(
                self.params, jnp.asarray(toks), jnp.asarray(lengths))
            self.cache = self._insert(self.cache, small,
                                      jnp.asarray(slots))
            self.stats["prefills"] += 1
            # sample every first generated token in one batch (padded
            # rows sampled too, then discarded)
            self._rng_key, sub = jax.random.split(self._rng_key)
            temps_np = np.zeros(n_pad, np.float32)
            top_ks_np = np.zeros(n_pad, np.int32)
            for row, (_, req) in enumerate(group):
                temps_np[row] = req.sampling.temperature
                top_ks_np[row] = req.sampling.top_k
            temps = jnp.asarray(temps_np)
            top_ks = jnp.asarray(top_ks_np)
            toks_out = np.asarray(
                self._sample(last_logits, temps, top_ks, sub))
            now = time.perf_counter()
            for row, (slot, req) in enumerate(group):
                req.first_token_at = now
                self.slots[slot] = req
                self.offsets[slot] = lengths[row]
                self._emit(slot, int(toks_out[row]))

    def _sample_one(self, logits_1d, req: Request):
        self._rng_key, sub = jax.random.split(self._rng_key)
        tok = self._sample(
            logits_1d[None, :],
            jnp.asarray([req.sampling.temperature], jnp.float32),
            jnp.asarray([req.sampling.top_k], jnp.int32), sub)
        return int(tok[0])

    def _decode_step(self) -> int:
        active = [i for i, r in enumerate(self.slots) if r is not None]
        if not active:
            return 0
        last_tokens = np.zeros(self.max_slots, np.int32)
        temps = np.zeros(self.max_slots, np.float32)
        top_ks = np.zeros(self.max_slots, np.int32)
        for i in active:
            req = self.slots[i]
            last_tokens[i] = req.output[-1] if req.output else \
                (req.prompt[-1] if req.prompt else 0)
            temps[i] = req.sampling.temperature
            top_ks[i] = req.sampling.top_k
        logits, self.cache = self._decode(
            self.params, self.cache, jnp.asarray(last_tokens),
            jnp.asarray(self.offsets))
        self._rng_key, sub = jax.random.split(self._rng_key)
        toks = np.asarray(self._sample(
            logits, jnp.asarray(temps), jnp.asarray(top_ks), sub))
        self.stats["decode_steps"] += 1
        for i in active:
            self.offsets[i] += 1
            self._emit(i, int(toks[i]))
        return len(active)

    def _emit(self, slot: int, tok: int) -> None:
        req = self.slots[slot]
        req.output.append(tok)
        req.stream.put(tok)
        self.stats["tokens_generated"] += 1
        stop = (tok in req.sampling.stop_token_ids
                or len(req.output) >= req.sampling.max_tokens
                or self.offsets[slot] + 1 >= self.max_seq)
        if stop:
            req.finish_reason = ("stop" if tok in req.sampling.stop_token_ids
                                 else "length")
            req.finished_at = time.perf_counter()
            req.stream.put(None)
            req.done.set()
            self.slots[slot] = None
            self.offsets[slot] = 0

    # -- prefill/decode disaggregation handoff -----------------------------
    def prefill_only(self, prompt_tokens: List[int]):
        """Prefill WITHOUT occupying a decode slot: returns
        (kv_small_numpy, last_logits_numpy, prompt_len) for transfer to a
        decode engine (reference: ray.llm prefill/decode disaggregation,
        `deployments/prefill_decode_disagg/`)."""
        n = len(prompt_tokens)
        bucket = self._bucket_for(n)
        if bucket is None:
            raise ValueError(f"prompt of {n} tokens exceeds buckets")
        toks = np.zeros((1, bucket), np.int32)
        toks[0, :n] = prompt_tokens
        last_logits, small = self._prefill(
            self.params, jnp.asarray(toks), jnp.asarray([n], np.int32))
        kv = {"k": np.asarray(small["k"]), "v": np.asarray(small["v"])}
        self.stats["prefills"] += 1
        return kv, np.asarray(last_logits[0]), n

    def submit_prefilled(self, prompt_tokens: List[int], kv: Dict,
                         last_logits, sampling: Optional[SamplingParams]
                         = None) -> Optional[Request]:
        """Admit a request whose prefill happened elsewhere. Returns None
        if no slot is free (caller retries)."""
        req = Request(prompt_tokens, sampling or SamplingParams())
        with self._lock:
            free = [i for i, s in enumerate(self.slots) if s is None]
            if not free:
                return None
            slot = free[0]
            small = {"k": jnp.asarray(kv["k"]), "v": jnp.asarray(kv["v"])}
            self.cache = self._insert(self.cache, small,
                                      jnp.asarray([slot], np.int32))
            tok = self._sample_one(jnp.asarray(last_logits), req)
            req.first_token_at = time.perf_counter()
            self.slots[slot] = req
            self.offsets[slot] = len(prompt_tokens)
            self.stats["requests"] += 1
            self._emit(slot, int(tok))
        return req

    # -- convenience -------------------------------------------------------
    def generate(self, prompts: List[List[int]],
                 sampling: Optional[SamplingParams] = None
                 ) -> List[Request]:
        reqs = [self.submit(p, sampling) for p in prompts]
        while self.has_work():
            self.step()
        return reqs

    def run_forever(self, stop_event: threading.Event,
                    idle_sleep_s: float = 0.002) -> None:
        """Background engine loop (used by the serving integration)."""
        while not stop_event.is_set():
            if self.step() == 0 and self.waiting.empty():
                time.sleep(idle_sleep_s)
