"""Serving benchmark harness: p50 TTFT + output tokens/sec.

Reference capability: the reference measures LLM serving with
``release/llm_tests/serve/benchmark/load_test.py:802-809`` (TTFT
percentiles + output token throughput). This is the in-tree TPU-native
equivalent, driven by ``BENCH_SERVE=1 python bench.py``: a burst of
synthetic requests through the continuous-batching engine, measuring
time-to-first-token per request and aggregate decode throughput.
"""

from __future__ import annotations

import os
import time
from typing import Optional


def _percentile(vals, q: float) -> float:
    """q in [0, 100]."""
    import numpy as np
    if not vals:
        return 0.0
    return float(np.percentile(vals, q, method="nearest"))


def run_serving_bench(error: Optional[str] = None) -> dict:
    import jax
    import numpy as np

    from ray_tpu.llm.engine import ContinuousBatchingEngine, SamplingParams
    from ray_tpu.models.llama import LlamaConfig, LlamaModel

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"

    if on_tpu:
        cfg = LlamaConfig.bench_400m(max_seq_len=1024)
        if os.environ.get("BENCH_DECODE"):   # "pallas" = paged kernel
            import dataclasses
            # replace() re-runs __post_init__ validation: a typo'd
            # kernel name must error, not silently bench the fallback
            cfg = dataclasses.replace(
                cfg, decode_attention=os.environ["BENCH_DECODE"])
        n_requests, max_tokens, max_slots = 96, 128, 32
        prompt_lo, prompt_hi = 32, 256
        n_prefix, prefix_len = 16, 128
    else:  # CPU smoke path
        cfg = LlamaConfig.debug(vocab_size=512, max_seq_len=128)
        n_requests, max_tokens, max_slots = 6, 8, 4
        prompt_lo, prompt_hi = 8, 24
        n_prefix, prefix_len = 3, 48   # 1 full block at the default bs=32

    model = LlamaModel(cfg)
    params = model.init(jax.random.key(0))
    engine = ContinuousBatchingEngine(
        model, params, max_slots=max_slots, max_seq=cfg.max_seq_len)

    rng = np.random.default_rng(0)
    prompts = [list(rng.integers(1, cfg.vocab_size,
                                 int(rng.integers(prompt_lo, prompt_hi))))
               for _ in range(n_requests)]

    # Warmup: jit-specialize EVERY prefill bucket a benchmark prompt can
    # hit (lengths are drawn from [prompt_lo, prompt_hi)), plus decode —
    # otherwise the first request per bucket pays an XLA compile inside
    # the timed region and TTFT measures compilation.
    limit = engine._bucket_for(prompt_hi - 1)
    assert limit is not None, "prompt_hi exceeds every prefill bucket"
    warm_buckets = [b for b in engine.buckets if b <= limit]
    engine.generate([[1] * b for b in warm_buckets],
                    SamplingParams(max_tokens=4))
    # Warm the PREFIX path too (gather + suffix prefill + scatter at the
    # same padded shapes the timed prefix phase hits) — a throwaway
    # prefix seeds, then a same-size hit wave compiles the batch shapes.
    wcommon = list(rng.integers(1, cfg.vocab_size, prefix_len))
    engine.generate([wcommon + [3, 4, 5]], SamplingParams(max_tokens=2))
    engine.generate([wcommon + [6 + i, 7, 8] for i in range(n_prefix)],
                    SamplingParams(max_tokens=2))

    t0 = time.perf_counter()
    reqs = [engine.submit(p, SamplingParams(max_tokens=max_tokens))
            for p in prompts]
    while engine.has_work():
        engine.step()
    wall = time.perf_counter() - t0

    ttfts = sorted(r.ttft_s for r in reqs if r.ttft_s is not None)
    output_tokens = sum(len(r.output) for r in reqs)
    tok_s = output_tokens / wall if wall > 0 else 0.0

    # Prefix-reuse phase: one request seals a long common prefix, then a
    # wave sharing it measures the cached-prefix TTFT win (the paged
    # pool's in-engine prefix cache, VERDICT r3 #5).
    common = list(rng.integers(1, cfg.vocab_size, prefix_len))
    engine.submit(common + [7, 8, 9], SamplingParams(max_tokens=4))
    while engine.has_work():
        engine.step()
    hits = [engine.submit(common + [30 + i, 41, 52 + i],
                          SamplingParams(max_tokens=16))
            for i in range(n_prefix)]
    while engine.has_work():
        engine.step()
    prefix_ttfts = sorted(r.ttft_s for r in hits if r.ttft_s is not None)
    out = {
        "metric": "llm_serve_output_tokens_per_sec",
        "value": round(tok_s, 1),
        "unit": "tokens/s",
        # No published reference serving numbers (BASELINE.md) — report
        # p50 TTFT (seconds) as the comparable headline alongside tok/s.
        "vs_baseline": round(_percentile(ttfts, 50), 4),
        "detail": {
            "ttft_p50_ms": round(_percentile(ttfts, 50) * 1e3, 2),
            "ttft_p90_ms": round(_percentile(ttfts, 90) * 1e3, 2),
            "ttft_p99_ms": round(_percentile(ttfts, 99) * 1e3, 2),
            "requests": n_requests,
            "output_tokens": output_tokens,
            "wall_s": round(wall, 3),
            "max_slots": max_slots,
            "max_tokens_per_req": max_tokens,
            "config": "llama_400m" if on_tpu else "debug",
            "device": getattr(dev, "device_kind", dev.platform),
            "ttft_prefix_hit_p50_ms": round(
                _percentile(prefix_ttfts, 50) * 1e3, 2),
            "prefix_prefills": engine.stats["prefix_prefills"],
            "prefix_tokens_reused": engine.stats["prefix_tokens_reused"],
            "preemptions": engine.stats["preemptions"],
            "block_size": engine.block_size,
            "num_blocks": engine.num_blocks,
        },
    }
    if error:
        out["error"] = error
    return out


def run_http_proxy_bench(error: Optional[str] = None) -> dict:
    """Proxy-level serving bench: p50 TTFT + output tok/s measured AT
    THE HTTP CLIENT through the asyncio ingress + Serve data plane +
    engine — the full serving path the reference drives
    (``release/llm_tests/serve/benchmark/load_test.py:802-809``), not
    the engine-direct numbers of :func:`run_serving_bench`."""
    import http.client
    import json
    import threading

    import jax
    import numpy as np

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.llm.serving import LLMConfig, build_llm_app
    from ray_tpu.models.llama import LlamaConfig

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    if on_tpu:
        model_cfg = LlamaConfig.bench_400m(max_seq_len=1024)
        n_requests, concurrency, max_tokens = 64, 16, 64
        prompt_len = 64
    else:
        model_cfg = None   # LLMServer debug config
        n_requests, concurrency, max_tokens = 8, 4, 8
        prompt_len = 12

    own = not ray_tpu.is_initialized()
    if own:
        ray_tpu.init(num_nodes=1, resources={"CPU": 8})
    cfg = LLMConfig(model_config=model_cfg, max_slots=16,
                    max_seq=(1024 if on_tpu else 128))
    serve.run(build_llm_app(cfg))
    port = serve.start_http_proxy(port=0, max_ongoing_requests=256)

    rng = np.random.default_rng(0)
    vocab = model_cfg.vocab_size if model_cfg else 512

    def one_request(out, idx):
        prompt = [int(x) for x in
                  rng.integers(1, vocab, prompt_len)]
        body = json.dumps({"prompt": prompt, "stream": True,
                           "max_tokens": max_tokens})
        conn = http.client.HTTPConnection("127.0.0.1", port,
                                          timeout=300)
        t0 = time.perf_counter()
        ttft = None
        tokens = 0
        try:
            conn.request("POST", "/", body=body,
                         headers={"Content-Type": "application/json",
                                  "Accept": "text/event-stream"})
            resp = conn.getresponse()
            buf = b""
            while True:
                chunk = resp.read(4096)
                if not chunk:
                    break
                buf += chunk
                while b"\n\n" in buf:
                    event, buf = buf.split(b"\n\n", 1)
                    if not event.startswith(b"data: "):
                        continue
                    data = event[6:]
                    if data == b"[DONE]":
                        break
                    if ttft is None:
                        ttft = time.perf_counter() - t0
                    try:
                        if "token_id" in json.loads(data):
                            tokens += 1
                    except json.JSONDecodeError:
                        pass
        finally:
            conn.close()
        out[idx] = (ttft, tokens)

    # warmup burst (compiles prefill/decode shapes outside the timing)
    warm: dict = {}
    warm_threads = [threading.Thread(target=one_request,
                                     args=(warm, i))
                    for i in range(min(concurrency, 4))]
    for t in warm_threads:
        t.start()
    for t in warm_threads:
        t.join()

    results: dict = {}
    t0 = time.perf_counter()
    sem = threading.Semaphore(concurrency)

    def gated(idx):
        with sem:
            one_request(results, idx)

    threads = [threading.Thread(target=gated, args=(i,))
               for i in range(n_requests)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0

    ttfts = sorted(t for t, _ in results.values() if t is not None)
    total_tokens = sum(n for _, n in results.values())
    out = {
        "metric": "llm_serve_http_output_tokens_per_sec",
        "value": round(total_tokens / wall, 1) if wall else 0.0,
        "unit": "tokens/s",
        "vs_baseline": round(_percentile(ttfts, 50), 4),
        "detail": {
            "ttft_p50_ms": round(_percentile(ttfts, 50) * 1e3, 2),
            "ttft_p90_ms": round(_percentile(ttfts, 90) * 1e3, 2),
            "requests": n_requests,
            "concurrency": concurrency,
            "output_tokens": total_tokens,
            "wall_s": round(wall, 3),
            "plane": "asyncio-http-proxy",
            "device": getattr(dev, "device_kind", dev.platform),
        },
    }
    serve.shutdown()
    if own:
        ray_tpu.shutdown()
    if error:
        out["error"] = error
    return out
