"""Serving benchmark harness: open-loop requests/s + TTFT percentiles.

Reference capability: the reference measures LLM serving with
``release/llm_tests/serve/benchmark/load_test.py:802-809`` (TTFT
percentiles + output token throughput). This is the in-tree TPU-native
equivalent, driven by ``BENCH_SERVE=1 python bench.py``: an OPEN-LOOP
load (``ray_tpu.loadgen``: seeded Poisson arrivals, concurrent client
workers, streaming TTFT at the client) against a real Serve app over
the continuous-batching engine — closed-loop bursts systematically
hide queueing collapse, so every serving row reports offered-rate
requests/s, TTFT/E2E percentiles, and goodput under an SLO
(``serving.*`` keys in the BENCH json; arXiv 2605.25645 methodology).
"""

from __future__ import annotations

import os
import time
from typing import Optional


def _percentile(vals, q: float) -> float:
    """q in [0, 100]."""
    import numpy as np
    if not vals:
        return 0.0
    return float(np.percentile(vals, q, method="nearest"))


def serving_section(report: dict) -> dict:
    """Flatten a loadgen report into the stable ``serving.*`` keys the
    BENCH json publishes (the driver greps these across rounds)."""
    good = report.get("goodput", {})
    return {
        "requests_per_second": report["requests_per_second"],
        "ttft_p50_s": report["ttft_s"]["p50"],
        "ttft_p99_s": report["ttft_s"]["p99"],
        "e2e_p50_s": report["e2e_s"]["p50"],
        "e2e_p99_s": report["e2e_s"]["p99"],
        "tpot_p50_s": report["tpot_s"]["p50"],
        "output_tokens_per_second": report["output_tokens_per_second"],
        "goodput_requests_per_second": good.get("requests_per_second",
                                                0.0),
        "goodput_fraction": good.get("fraction", 0.0),
        "slo": good.get("slo", {}),
        "offered_rate": report["spec"]["rate"],
        "arrival": report["spec"]["arrival"],
        "clients": report["spec"]["clients"],
        "completed": report["requests"]["completed"],
        "errors": report["requests"]["errors"],
        "open_loop": True,
    }


def run_serving_bench(error: Optional[str] = None) -> dict:
    """Open-loop serving bench through the full Serve data plane:
    handle -> depth-aware P2C router -> replica -> engine, measured at
    the client (streaming chunks, so TTFT is real)."""
    import jax

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.llm.serving import LLMConfig, build_llm_app
    from ray_tpu.loadgen import SLO, HandleTarget, LoadSpec, run_load
    from ray_tpu.models.llama import LlamaConfig

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"

    if on_tpu:
        model_cfg = LlamaConfig.bench_400m(max_seq_len=1024)
        if os.environ.get("BENCH_DECODE"):   # "pallas" = paged kernel
            import dataclasses
            # replace() re-runs __post_init__ validation: a typo'd
            # kernel name must error, not silently bench the fallback
            cfg_err = dataclasses.replace(
                model_cfg, decode_attention=os.environ["BENCH_DECODE"])
            model_cfg = cfg_err
        replicas, max_slots, max_seq = 1, 32, 1024
        spec = LoadSpec(rate=6.0, duration_s=16.0, clients=64,
                        prompt_len="uniform:32:256", output_len=64,
                        vocab=model_cfg.vocab_size, seed=0,
                        slo=SLO(ttft_s=2.0, e2e_s=30.0))
        # EVERY engine prefill bucket (32, 64, 128, 256, 512) a
        # uniform:32:256 prompt can land in — a cold bucket pays XLA
        # compile inside the timed window
        warm_lens = (32, 64, 128, 256)
    else:  # CPU smoke path (debug model, small burst)
        model_cfg = None    # LLMServer debug config
        replicas, max_slots, max_seq = 2, 4, 128
        spec = LoadSpec(rate=12.0, duration_s=2.5, clients=8,
                        prompt_len="uniform:8:24", output_len=8,
                        vocab=500, seed=0,
                        slo=SLO(ttft_s=1.0, e2e_s=5.0))
        warm_lens = (8, 24)

    own = not ray_tpu.is_initialized()
    if own:
        ray_tpu.init(num_nodes=1, resources={"CPU": 8})
    cfg = LLMConfig(model_id="bench-serving", model_config=model_cfg,
                    max_slots=max_slots, max_seq=max_seq,
                    num_replicas=replicas)
    handle = serve.run(build_llm_app(cfg))

    # Warm EVERY replica's engine at the prompt buckets the load can
    # hit (plus decode + the streaming path) — a cold replica's first
    # TTFT otherwise measures XLA compile, not serving.
    controller = ray_tpu.get_actor("serve_controller")
    reps = ray_tpu.get(
        controller.get_replicas.remote(cfg.model_id))["replicas"]
    warm = [{"prompt": [1] * n, "max_tokens": 2} for n in warm_lens]
    ray_tpu.get([r.handle_request.remote("__call__", (w,), {})
                 for r in reps for w in warm], timeout=600)

    report = run_load(HandleTarget(handle, stream=True,
                                   timeout_s=spec.timeout_s), spec)
    engine_stats = {}
    try:
        engine_stats = ray_tpu.get(reps[0].handle_request.remote(
            "stats", (), {}), timeout=30)
    except Exception:
        pass
    serve.shutdown()
    if own:
        ray_tpu.shutdown()

    serving = serving_section(report)
    serving["replicas"] = replicas
    out = {
        "metric": "llm_serve_requests_per_second",
        "value": serving["requests_per_second"],
        "unit": "req/s",
        # No published reference serving numbers (BASELINE.md) — report
        # p50 TTFT (seconds) as the comparable headline alongside req/s.
        "vs_baseline": round(serving["ttft_p50_s"], 4),
        "serving": serving,
        "detail": {
            **report,
            "max_slots": max_slots,
            "config": "llama_400m" if on_tpu else "debug",
            "device": getattr(dev, "device_kind", dev.platform),
            "engine_stats": engine_stats,
        },
        "platform": dev.platform,
        "tpu_fallback": not on_tpu,
    }
    if error:
        out["error"] = error
    return out


def run_http_proxy_bench(error: Optional[str] = None) -> dict:
    """Proxy-level serving bench: p50 TTFT + output tok/s measured AT
    THE HTTP CLIENT through the asyncio ingress + Serve data plane +
    engine — the full serving path the reference drives
    (``release/llm_tests/serve/benchmark/load_test.py:802-809``), not
    the engine-direct numbers of :func:`run_serving_bench`."""
    import http.client
    import json
    import threading

    import jax
    import numpy as np

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.llm.serving import LLMConfig, build_llm_app
    from ray_tpu.models.llama import LlamaConfig

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    if on_tpu:
        model_cfg = LlamaConfig.bench_400m(max_seq_len=1024)
        n_requests, concurrency, max_tokens = 64, 16, 64
        prompt_len = 64
    else:
        model_cfg = None   # LLMServer debug config
        n_requests, concurrency, max_tokens = 8, 4, 8
        prompt_len = 12

    own = not ray_tpu.is_initialized()
    if own:
        ray_tpu.init(num_nodes=1, resources={"CPU": 8})
    cfg = LLMConfig(model_config=model_cfg, max_slots=16,
                    max_seq=(1024 if on_tpu else 128))
    serve.run(build_llm_app(cfg))
    port = serve.start_http_proxy(port=0, max_ongoing_requests=256)

    rng = np.random.default_rng(0)
    vocab = model_cfg.vocab_size if model_cfg else 512

    def one_request(out, idx):
        prompt = [int(x) for x in
                  rng.integers(1, vocab, prompt_len)]
        body = json.dumps({"prompt": prompt, "stream": True,
                           "max_tokens": max_tokens})
        conn = http.client.HTTPConnection("127.0.0.1", port,
                                          timeout=300)
        t0 = time.perf_counter()
        ttft = None
        tokens = 0
        try:
            conn.request("POST", "/", body=body,
                         headers={"Content-Type": "application/json",
                                  "Accept": "text/event-stream"})
            resp = conn.getresponse()
            buf = b""
            while True:
                chunk = resp.read(4096)
                if not chunk:
                    break
                buf += chunk
                while b"\n\n" in buf:
                    event, buf = buf.split(b"\n\n", 1)
                    if not event.startswith(b"data: "):
                        continue
                    data = event[6:]
                    if data == b"[DONE]":
                        break
                    if ttft is None:
                        ttft = time.perf_counter() - t0
                    try:
                        if "token_id" in json.loads(data):
                            tokens += 1
                    except json.JSONDecodeError:
                        pass
        finally:
            conn.close()
        out[idx] = (ttft, tokens)

    # warmup burst (compiles prefill/decode shapes outside the timing)
    warm: dict = {}
    warm_threads = [threading.Thread(target=one_request,
                                     args=(warm, i))
                    for i in range(min(concurrency, 4))]
    for t in warm_threads:
        t.start()
    for t in warm_threads:
        t.join()

    results: dict = {}
    t0 = time.perf_counter()
    sem = threading.Semaphore(concurrency)

    def gated(idx):
        with sem:
            one_request(results, idx)

    threads = [threading.Thread(target=gated, args=(i,))
               for i in range(n_requests)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0

    ttfts = sorted(t for t, _ in results.values() if t is not None)
    total_tokens = sum(n for _, n in results.values())
    out = {
        "metric": "llm_serve_http_output_tokens_per_sec",
        "value": round(total_tokens / wall, 1) if wall else 0.0,
        "unit": "tokens/s",
        "vs_baseline": round(_percentile(ttfts, 50), 4),
        "detail": {
            "ttft_p50_ms": round(_percentile(ttfts, 50) * 1e3, 2),
            "ttft_p90_ms": round(_percentile(ttfts, 90) * 1e3, 2),
            "requests": n_requests,
            "concurrency": concurrency,
            "output_tokens": total_tokens,
            "wall_s": round(wall, 3),
            "plane": "asyncio-http-proxy",
            "device": getattr(dev, "device_kind", dev.platform),
        },
        "platform": dev.platform,
        "tpu_fallback": not on_tpu,
    }
    serve.shutdown()
    if own:
        ray_tpu.shutdown()
    if error:
        out["error"] = error
    return out
