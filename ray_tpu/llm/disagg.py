"""Prefill/decode disaggregation.

Reference: `llm/_internal/serve/deployments/prefill_decode_disagg/` —
prefill replicas (compute-bound) and decode replicas (HBM-bandwidth-
bound) scale independently; the prompt's KV cache transfers between them
(reference: NIXL/NCCL; here the object plane carries the arrays — on a
pod this is an ICI/DCN device-to-device transfer).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from ray_tpu import serve
from ray_tpu.llm.engine import SamplingParams
from ray_tpu.llm.serving import LLMConfig
from ray_tpu.llm.tokenizer import ByteTokenizer, load_tokenizer


def _build_model(config: LLMConfig):
    import jax
    from ray_tpu.models.llama import LlamaConfig, LlamaModel
    cfg = config.model_config or LlamaConfig.debug(
        vocab_size=512, max_seq_len=config.max_seq)
    model = LlamaModel(cfg)
    params = model.init(jax.random.key(config.seed))
    return model, params


class PrefillServer:
    """Compute-bound plane: prompt → (kv, first-token logits)."""

    def __init__(self, config: LLMConfig):
        from ray_tpu.llm.engine import ContinuousBatchingEngine
        model, params = _build_model(config)
        self.engine = ContinuousBatchingEngine(
            model, params, max_slots=1, max_seq=config.max_seq,
            block_size=config.block_size,
            num_blocks=config.num_blocks)
        self.tokenizer = (load_tokenizer(config.tokenizer)
                          if config.tokenizer else ByteTokenizer())

    def prefill(self, prompt) -> Dict[str, Any]:
        ids = (prompt if isinstance(prompt, list)
               else self.tokenizer.encode(prompt))
        kv, last_logits, n = self.engine.prefill_only(ids)
        return {"kv": kv, "last_logits": last_logits, "prompt_ids": ids}


class DecodeServer:
    """Bandwidth-bound plane: continues generation from transferred KV."""

    def __init__(self, config: LLMConfig):
        from ray_tpu.llm.engine import ContinuousBatchingEngine
        model, params = _build_model(config)
        self.engine = ContinuousBatchingEngine(
            model, params, max_slots=config.max_slots,
            max_seq=config.max_seq, block_size=config.block_size,
            num_blocks=config.num_blocks)
        self.tokenizer = (load_tokenizer(config.tokenizer)
                          if config.tokenizer else ByteTokenizer())
        self._stop = threading.Event()
        threading.Thread(target=self.engine.run_forever,
                         args=(self._stop,), daemon=True).start()

    def decode(self, prefill_out: Dict[str, Any],
               max_tokens: int = 32, temperature: float = 0.0
               ) -> Dict[str, Any]:
        sampling = SamplingParams(max_tokens=max_tokens,
                                  temperature=temperature)
        req = None
        deadline = time.time() + 300
        while req is None and time.time() < deadline:
            req = self.engine.submit_prefilled(
                prefill_out["prompt_ids"], prefill_out["kv"],
                prefill_out["last_logits"], sampling)
            if req is None:
                time.sleep(0.01)   # all slots busy: continuous batching
        if req is None:
            raise TimeoutError("no decode slot became free")
        req.done.wait(timeout=300)
        return {"token_ids": list(req.output),
                "text": self.tokenizer.decode(req.output),
                "finish_reason": req.finish_reason,
                "ttft_s": req.ttft_s}


class PDOrchestrator:
    """Ingress: prefill handle → decode handle (the `1p1d`-style graph)."""

    def __init__(self, prefill_handle, decode_handle):
        self.prefill = prefill_handle
        self.decode = decode_handle

    def __call__(self, request: Dict[str, Any]) -> Dict[str, Any]:
        pre = self.prefill.prefill.remote(request["prompt"]).result()
        return self.decode.decode.remote(
            pre, request.get("max_tokens", 32),
            request.get("temperature", 0.0)).result()


def build_pd_disagg_app(config: LLMConfig, *, num_prefill: int = 1,
                        num_decode: int = 1) -> serve.Application:
    """`build_pd_openai_app` equivalent (reference: serve config with
    prefill_config/decode_config)."""
    prefill_dep = serve.deployment(
        PrefillServer, name=f"{config.model_id}-prefill",
        num_replicas=num_prefill)
    decode_dep = serve.deployment(
        DecodeServer, name=f"{config.model_id}-decode",
        num_replicas=num_decode)
    orchestrator = serve.deployment(
        PDOrchestrator, name=f"{config.model_id}-pd")
    return orchestrator.bind(prefill_dep.bind(config),
                             decode_dep.bind(config))
