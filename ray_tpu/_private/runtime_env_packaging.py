"""Runtime-env package materialization (working_dir / py_modules).

Reference capability: ``_private/runtime_env/{packaging,working_dir,
py_modules}.py`` — the driver zips the directory, publishes it under a
content-addressed ``gcs://`` URI, and every worker downloads + extracts
it once into a node-local cache before running tasks.

Same shape here: the driver packages a directory into an in-memory zip
registered in a content-addressed table (the function-table pattern);
workers fetch the blob through the owner core-op channel
(``fetch_runtime_pkg``) and extract into ``/tmp/ray_tpu/pkg_cache/<hash>``
— so a ``runtime_env={"working_dir": ...}`` works even when the worker
process (or daemon host) never saw the original path.
"""

from __future__ import annotations

import hashlib
import io
import os
import threading
import zipfile
from typing import Dict, List, Optional, Tuple

PKG_SCHEME = "pkg://"
_CACHE_ROOT = "/tmp/ray_tpu/pkg_cache"

_TABLE: Dict[str, bytes] = {}
_TABLE_LOCK = threading.Lock()
_DIR_MEMO: Dict[Tuple[str, float], str] = {}   # (path, mtime) -> uri


def _should_exclude(rel: str, excludes: List[str]) -> bool:
    import fnmatch

    return any(fnmatch.fnmatch(rel, pat) or fnmatch.fnmatch(
        os.path.basename(rel), pat) for pat in excludes)


def package_directory(path: str,
                      excludes: Optional[List[str]] = None) -> str:
    """Zip ``path`` and register the blob; returns its ``pkg://`` URI.
    Content-addressed: identical trees share one entry; an unchanged
    directory (same newest mtime) skips re-zipping."""
    path = os.path.abspath(path)
    if not os.path.isdir(path):
        raise ValueError(f"runtime_env directory not found: {path}")
    excludes = list(excludes or []) + ["__pycache__", "*.pyc"]
    newest = os.path.getmtime(path)
    entries = []
    for root, dirs, files in os.walk(path):
        dirs[:] = [d for d in dirs if not _should_exclude(
            os.path.relpath(os.path.join(root, d), path), excludes)]
        # directory mtimes catch DELETIONS inside subdirs (removing a
        # file bumps only its parent dir's mtime)
        newest = max(newest, os.path.getmtime(root))
        for f in sorted(files):
            full = os.path.join(root, f)
            rel = os.path.relpath(full, path)
            if _should_exclude(rel, excludes):
                continue
            entries.append((rel, full))
            newest = max(newest, os.path.getmtime(full))
    memo_key = (path, newest)
    cached = _DIR_MEMO.get(memo_key)
    if cached is not None:
        return cached

    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for rel, full in entries:
            zf.write(full, rel)
    blob = buf.getvalue()
    digest = hashlib.sha1(blob).hexdigest()
    uri = PKG_SCHEME + digest
    with _TABLE_LOCK:
        _TABLE[digest] = blob
    _DIR_MEMO[memo_key] = uri
    return uri


def fetch_pkg_blob(uri: str) -> bytes:
    """Driver-side lookup (served to workers via the core-op channel)."""
    digest = uri[len(PKG_SCHEME):]
    with _TABLE_LOCK:
        blob = _TABLE.get(digest)
    if blob is None:
        raise KeyError(f"runtime-env package {uri} not in table")
    return blob


def cached_dir(uri: str) -> Optional[str]:
    """Already-extracted local directory for ``uri``, if any."""
    digest = uri[len(PKG_SCHEME):]
    target = os.path.join(_CACHE_ROOT, digest)
    return target if os.path.isdir(target) else None


def extract_blob(uri: str, blob: bytes) -> str:
    """Extract into the node-local cache (idempotent, atomic rename)."""
    digest = uri[len(PKG_SCHEME):]
    target = os.path.join(_CACHE_ROOT, digest)
    if os.path.isdir(target):
        return target
    tmp = target + f".tmp{os.getpid()}"
    os.makedirs(tmp, exist_ok=True)
    with zipfile.ZipFile(io.BytesIO(blob)) as zf:
        zf.extractall(tmp)
    try:
        os.rename(tmp, target)
    except OSError:
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)  # concurrent extractor won
    return target


def resolve_local(uri: str) -> str:
    """pkg:// URI -> local dir, for processes holding the table (driver)
    or with a warm cache (workers resolve via their host channel first)."""
    local = cached_dir(uri)
    if local is not None:
        return local
    return extract_blob(uri, fetch_pkg_blob(uri))


_PREPARED: Dict[int, Tuple[tuple, dict, float]] = {}
_PREPARED_TTL = 0.25   # seconds between tree re-validations


def _job_default_runtime_env():
    from ray_tpu._private import worker

    rt = worker.global_runtime()
    return getattr(rt, "_job_default_env", None)


def prepare_runtime_env(runtime_env):
    """Driver-side, at submission: package directory-valued
    working_dir/py_modules into pkg:// URIs so the env materializes on
    any worker anywhere (reference: upload_package_to_gcs).

    Submission hot path: the prepared result is memoized per
    runtime_env dict (a decorator's options dict is stable across
    .remote() calls) — but only for ``_PREPARED_TTL``: edits to a
    working_dir between submissions must be re-packaged, and only the
    tree walk in ``package_directory`` (which fingerprints by newest
    mtime and skips re-zipping when unchanged) can see them. The TTL
    amortizes that walk over hot submission loops without letting
    workers run stale code for the process lifetime."""
    if not runtime_env:
        # job-level default (reference: JobConfig.runtime_env applied
        # when a task/actor declares none — job_config.py serialize ->
        # worker.py connect)
        runtime_env = _job_default_runtime_env()
    if not runtime_env:
        return runtime_env
    import time as _time

    fingerprint = (runtime_env.get("working_dir"),
                   tuple(runtime_env.get("py_modules") or ()))
    now = _time.monotonic()
    cached = _PREPARED.get(id(runtime_env))
    if (cached is not None and cached[0] == fingerprint
            and now - cached[2] < _PREPARED_TTL):
        return cached[1]
    out = dict(runtime_env)
    excludes = out.get("excludes") or []
    wd = out.get("working_dir")
    if wd and not str(wd).startswith(PKG_SCHEME) and os.path.isdir(wd):
        out["working_dir"] = package_directory(wd, excludes)
    mods = out.get("py_modules")
    if mods:
        out["py_modules"] = [
            package_directory(m, excludes)
            if not str(m).startswith(PKG_SCHEME) and os.path.isdir(m)
            else m for m in mods]
    if len(_PREPARED) > 256:
        _PREPARED.clear()   # unbounded decorator churn backstop
    _PREPARED[id(runtime_env)] = (fingerprint, out, now)
    return out
