"""Memory monitor + worker-killing policies (OOM defense).

Reference capability: ``src/ray/common/memory_monitor.h:52`` samples
system/cgroup memory against a usage threshold; on breach the raylet
applies a worker-killing policy (``raylet/worker_killing_policy*.h``):
``retriable-FIFO`` prefers the newest retriable work, ``group-by-owner``
penalizes the owner with the most submitted tasks. Killing a retriable
task's worker converts an imminent host OOM (which would take down the
whole node, driver included) into a task retry; when retries are
exhausted the task fails with :class:`OutOfMemoryError`.

TPU note: this guards HOST memory only. Device HBM pressure is handled
by XLA allocation failures inside the mesh-owning process and by the
object store's create/eviction backpressure — a host monitor must never
SIGKILL the process that owns the TPU client, so only worker processes
(never the driver) are candidates.
"""

from __future__ import annotations

import os
import signal
import threading
import time
from typing import Any, List, Optional

def _flag(name):
    from ray_tpu._private.config import cfg
    return getattr(cfg(), name)


# kept as module names for back-compat; resolved through the central
# flag table (ray_tpu/_private/config.py, ray_config_def.h role)
CHECK_INTERVAL_S = None   # -> cfg().memory_monitor_interval
USAGE_THRESHOLD = None    # -> cfg().memory_usage_threshold


def _cgroup_limit() -> Optional[int]:
    for path in ("/sys/fs/cgroup/memory.max",
                 "/sys/fs/cgroup/memory/memory.limit_in_bytes"):
        try:
            raw = open(path).read().strip()
            if raw and raw != "max":
                val = int(raw)
                if 0 < val < 1 << 60:
                    return val
        except (OSError, ValueError):
            continue
    return None


def system_memory_limit() -> int:
    limit = _cgroup_limit()
    if limit is not None:
        return limit
    try:
        for line in open("/proc/meminfo"):
            if line.startswith("MemTotal:"):
                return int(line.split()[1]) * 1024
    except OSError:
        pass
    return 1 << 62


def _cgroup_reclaimable() -> int:
    """inactive file-backed pages: the kernel reclaims these before
    OOMing, so they must not count as pressure (the reference monitor
    subtracts cache/available for the same reason)."""
    for path in ("/sys/fs/cgroup/memory.stat",
                 "/sys/fs/cgroup/memory/memory.stat"):
        try:
            for line in open(path):
                if line.startswith("inactive_file "):
                    return int(line.split()[1])
        except (OSError, ValueError):
            continue
    return 0


def _cgroup_current() -> Optional[int]:
    for path in ("/sys/fs/cgroup/memory.current",
                 "/sys/fs/cgroup/memory/memory.usage_in_bytes"):
        try:
            used = int(open(path).read().strip())
            return max(used - _cgroup_reclaimable(), 0)
        except (OSError, ValueError):
            continue
    return None


def process_rss(pid: int) -> int:
    """Proportional set size when available (shared pages — the shm
    object arena, forkserver template — counted once per sharer), RSS
    as fallback."""
    try:
        with open(f"/proc/{pid}/smaps_rollup") as f:
            for line in f:
                if line.startswith("Pss:"):
                    return int(line.split()[1]) * 1024
    except (OSError, IndexError, ValueError):
        pass
    try:
        with open(f"/proc/{pid}/statm") as f:
            return int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
    except (OSError, IndexError, ValueError):
        return 0


class _Candidate:
    __slots__ = ("pid", "kind", "task_id", "actor_id", "retriable",
                 "started_at", "owner_key")

    def __init__(self, pid, kind, task_id=None, actor_id=None,
                 retriable=True, started_at=0.0, owner_key=""):
        self.pid = pid
        self.kind = kind                # "task" | "actor"
        self.task_id = task_id
        self.actor_id = actor_id
        self.retriable = retriable
        self.started_at = started_at
        self.owner_key = owner_key


class RetriableFIFOPolicy:
    """Prefer the NEWEST retriable task (cheapest progress to lose);
    fall back to the newest restartable actor, then anything
    (reference: worker_killing_policy_retriable_fifo.h)."""

    def pick(self, candidates: List[_Candidate]) -> Optional[_Candidate]:
        for pool in (
                [c for c in candidates if c.kind == "task" and c.retriable],
                [c for c in candidates if c.kind == "actor"
                 and c.retriable],
                candidates):
            if pool:
                return max(pool, key=lambda c: c.started_at)
        return None


class GroupByOwnerPolicy:
    """Penalize the owner group with the most running work; newest first
    within the group (reference: worker_killing_policy_group_by_owner.h).
    Here every task shares one owner (the single controller), so groups
    are keyed by task name — a fan-out that floods memory gets trimmed
    before unrelated singleton work dies."""

    def pick(self, candidates: List[_Candidate]) -> Optional[_Candidate]:
        groups: dict = {}
        for c in candidates:
            groups.setdefault(c.owner_key, []).append(c)
        if not groups:
            return None
        biggest = max(groups.values(), key=len)
        retriable = [c for c in biggest if c.retriable]
        pool = retriable or biggest
        return max(pool, key=lambda c: c.started_at)


class TenantAwarePolicy:
    """Point preemption at over-quota tenants first (the graceful-
    degradation tier of docs/fault_tolerance.md "Memory pressure"):
    when the driver's fair-share ledger marks jobs at/over a hard cap
    (synced to daemons via ``tenancy_sync``), their workers are
    preferred victims; the wrapped policy still orders WITHIN the
    preferred pool, and the full pool backstops when no over-quota
    worker runs here. ``last_reason`` feeds the
    ``ray_tpu_oom_preemptions_total{reason}`` counter."""

    def __init__(self, inner: Any, over_quota_fn: Any):
        self.inner = inner
        self.over_quota_fn = over_quota_fn
        self.last_reason = "host"

    def pick(self, candidates: List[_Candidate]) -> Optional[_Candidate]:
        over = set()
        try:
            over = set(self.over_quota_fn() or ())
        except Exception:
            pass
        if over:
            preferred = [c for c in candidates if c.owner_key in over]
            if preferred:
                victim = self.inner.pick(preferred)
                if victim is not None:
                    self.last_reason = "tenant_quota"
                    return victim
        self.last_reason = "host"
        return self.inner.pick(candidates)


class MemoryMonitor:
    """Samples driver+worker RSS; on threshold breach kills one worker
    process per tick using the configured policy."""

    def __init__(self, runtime, limit_bytes: Optional[int] = None,
                 threshold: float = USAGE_THRESHOLD,
                 policy: Optional[Any] = None,
                 interval_s: Optional[float] = None,
                 candidates_fn: Optional[Any] = None):
        self.runtime = runtime
        # custom candidate source (the daemon-side monitor: its worker
        # pool is not a driver Runtime; reference: the raylet's monitor
        # watches ITS node's workers, node_manager-side)
        self.candidates_fn = candidates_fn
        self.limit = limit_bytes or _flag("memory_limit_bytes") or \
            system_memory_limit()
        self.threshold = threshold if threshold is not None \
            else _flag("memory_usage_threshold")
        self.policy = policy or (
            GroupByOwnerPolicy()
            if _flag("worker_killing_policy") == "group_by_owner"
            else RetriableFIFOPolicy())
        self.interval_s = (interval_s if interval_s is not None
                           else _flag("memory_monitor_interval"))
        self.kills = 0
        self.oom_killed_tasks: set = set()
        self.oom_killed_actors: set = set()
        self.kill_log: List[Any] = []   # (pid, wall ts) per OOM kill
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="memory-monitor")

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def set_limit(self, limit_bytes: int) -> None:
        self.limit = limit_bytes
        self._explicit_limit = limit_bytes   # replayed to late joiners
        # cluster-wide: node daemons enforce on THEIR workers (the
        # raylet-side monitor); forward the new limit to each
        backend = getattr(self.runtime, "cluster_backend", None) \
            if self.runtime is not None else None
        if backend is not None:
            for handle in list(backend.daemons.values()):
                try:
                    handle.client.call("set_memory_limit",
                                       limit=limit_bytes, timeout=5.0)
                except Exception:
                    pass

    # -- sampling ---------------------------------------------------------
    def _worker_pids(self):
        """(pid, candidate) for every live worker process."""
        if self.candidates_fn is not None:
            return list(self.candidates_fn())
        router = self.runtime.process_router
        out: List[_Candidate] = []
        with router._lock:
            running = dict(router._running)
            actors = dict(router._actor_workers)
        with self.runtime._tasks_lock:
            tasks = dict(self.runtime._tasks)
        for task_id, (client, _rid) in running.items():
            inflight = tasks.get(task_id)
            spec = inflight.spec if inflight else None
            retriable = bool(spec is not None
                             and (spec.max_retries != 0))
            out.append(_Candidate(
                client.proc.pid, "task", task_id=task_id,
                retriable=retriable,
                started_at=getattr(spec, "enqueued_at", 0.0) or 0.0,
                owner_key=getattr(spec, "name", "")))
        for actor_id, client in actors.items():
            info = self.runtime.gcs.get_actor_info(actor_id)
            restartable = bool(info is not None
                               and (info.max_restarts == -1
                                    or info.num_restarts
                                    < info.max_restarts))
            out.append(_Candidate(
                client.proc.pid, "actor", actor_id=actor_id,
                retriable=restartable,
                started_at=getattr(client, "actor_since", 0.0),
                owner_key=getattr(info, "class_name", "") or ""))
        # driver-local fast-lane workers: their task ids live in the
        # native core, so kills are un-attributed (time-window
        # attribution in the crash handler, like the daemon's lane)
        for w in list(getattr(router, "_fast_workers", [])):
            if w.alive():
                out.append(_Candidate(
                    w.proc.pid, "task", retriable=True,
                    started_at=0.0, owner_key="fast-lane"))
        return out

    def usage_bytes(self, candidates=None) -> int:
        # Prefer the cgroup's own accounting (one number, shared pages
        # counted once — reference memory_monitor.h samples system used
        # memory for exactly this reason); PSS summation is the
        # fallback outside a memory cgroup.
        current = _cgroup_current()
        if current is not None:
            return current
        total = process_rss(os.getpid())
        for cand in candidates or self._worker_pids():
            total += process_rss(cand.pid)
        return total

    # -- enforcement ------------------------------------------------------
    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self._tick()
            except Exception:
                pass

    def _tick(self) -> None:
        candidates = self._worker_pids()
        used = self.usage_bytes(candidates)
        if used < self.limit * self.threshold:
            return
        victim = self.policy.pick(candidates)
        if victim is None:
            return
        self.kills += 1
        import time as _time
        attributed = (victim.task_id is not None
                      or victim.actor_id is not None)
        self.kill_log.append((victim.pid, _time.time(), attributed))
        del self.kill_log[:-100]          # bounded
        if victim.task_id is not None:
            self.oom_killed_tasks.add(victim.task_id)
        if victim.actor_id is not None:
            self.oom_killed_actors.add(victim.actor_id)
        try:
            from ray_tpu._private.pressure import count_oom_preemption
            count_oom_preemption(
                getattr(self.policy, "last_reason", "host") or "host")
        except Exception:
            pass
        try:
            os.kill(victim.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass

    def was_oom_killed(self, task_id) -> bool:
        return task_id in self.oom_killed_tasks

    def consume_unattributed_kill(self, window_s: float = 60.0) -> bool:
        """Claim ONE un-attributed OOM kill (fast-lane workers — their
        task ids live in the native core) within the window. Consuming
        the entry means one kill explains one crash; it cannot keep
        painting later, unrelated crashes as OOM."""
        import time as _time
        now = _time.time()
        for i in range(len(self.kill_log) - 1, -1, -1):
            pid, ts, attributed = self.kill_log[i]
            if not attributed and now - ts < window_s:
                self.kill_log[i] = (pid, ts, True)   # claimed
                return True
        return False
