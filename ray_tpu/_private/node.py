"""Per-node runtime: resource accounting, task dispatch, actor hosting.

Parity contract (reference ``src/ray/raylet/``): each node owns a resource
ledger (``LocalResourceManager``), a queue of leased tasks gated on resource
availability (``LocalTaskManager``), a worker pool that executes them, and the
actor executors living on the node. Worker leases are implicit: the scheduler
(:mod:`ray_tpu._private.scheduler`) assigns a task to a node, the node's
dispatch loop admits it when resources free up, and a pooled worker thread
runs it.

TPU-first note: heavy compute on this framework happens inside XLA executables
which release the GIL, so a thread-based worker pool gives real parallelism
for accelerator work; CPU-bound Python tasks still interleave. The dispatch /
resource model is process-agnostic so a subprocess worker pool can slot in.
"""

from __future__ import annotations

import heapq
import queue
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Callable, Dict, List, Optional

from ray_tpu._private.gcs import NodeInfo
from ray_tpu._private.ids import ActorID, NodeID
from ray_tpu._private.lock_sanitizer import tracked_lock
from ray_tpu._private.object_store import LocalObjectStore
from ray_tpu._private.task_spec import TaskKind, TaskSpec
from ray_tpu.util import metrics as _metrics

_DISPATCH_POLL_S = 5.0

# Queue sentinel that only wakes the dispatch loop (None means exit).
_WAKE = object()


def _bump_cluster_epoch() -> None:
    # lazy import: scheduler.py imports this module at top level
    from ray_tpu._private.scheduler import bump_cluster_epoch
    bump_cluster_epoch()


class ResourceLedger:
    """Tracks total/available resources with blocking acquire."""

    def __init__(self, total: Dict[str, float]):
        self.total = dict(total)
        self._available = dict(total)
        self._cond = threading.Condition()
        # availability-grew hook (async dispatch): fired OUTSIDE the
        # condition lock after release/release_many/add_total so a
        # loop-hosted dispatch pass wakes immediately instead of
        # polling wait_for_change. The threaded dispatch loop keeps
        # using the condition and never sets this.
        self.on_change: Optional[Callable[[], None]] = None

    def _fire_on_change(self) -> None:
        cb = self.on_change
        if cb is not None:
            try:
                cb()
            except Exception:
                pass    # a wake hook must never fail a release

    def can_fit_total(self, demand: Dict[str, float]) -> bool:
        return all(self.total.get(k, 0.0) >= v for k, v in demand.items())

    def try_acquire(self, demand: Dict[str, float]) -> bool:
        with self._cond:
            if all(self._available.get(k, 0.0) >= v - 1e-9
                   for k, v in demand.items()):
                for k, v in demand.items():
                    self._available[k] = self._available.get(k, 0.0) - v
                return True
            return False

    def release(self, demand: Dict[str, float]) -> None:
        with self._cond:
            for k, v in demand.items():
                self._available[k] = min(
                    self._available.get(k, 0.0) + v, self.total.get(k, 0.0))
            self._cond.notify_all()
        self._fire_on_change()

    def wait_for_change(self, timeout: float) -> None:
        with self._cond:
            self._cond.wait(timeout)

    def notify(self) -> None:
        with self._cond:
            self._cond.notify_all()

    def available(self) -> Dict[str, float]:
        with self._cond:
            return dict(self._available)

    def add_total(self, extra: Dict[str, float]) -> None:
        """Grow capacity in place (placement-group bundle resources)."""
        with self._cond:
            for k, v in extra.items():
                self.total[k] = self.total.get(k, 0.0) + v
                self._available[k] = self._available.get(k, 0.0) + v
            self._cond.notify_all()
        self._fire_on_change()
        _bump_cluster_epoch()   # can_fit_total answers changed

    def remove_total(self, extra: Dict[str, float]) -> None:
        with self._cond:
            for k, v in extra.items():
                self.total[k] = max(self.total.get(k, 0.0) - v, 0.0)
                self._available[k] = max(self._available.get(k, 0.0) - v, 0.0)
            self._cond.notify_all()
        _bump_cluster_epoch()

    def try_acquire_many(self, demand: Dict[str, float],
                         max_n: int) -> int:
        """Admit as many identically-shaped demands as fit — computed
        and deducted under ONE lock acquisition (the dispatch loop's
        batch admission; per-task try_acquire paid a lock round-trip
        per queued task)."""
        if max_n <= 0:
            return 0
        with self._cond:
            n = max_n
            for k, v in demand.items():
                if v <= 0:
                    continue
                have = self._available.get(k, 0.0)
                n = min(n, int((have + 1e-9) // v))
                if n <= 0:
                    return 0
            for k, v in demand.items():
                self._available[k] = self._available.get(k, 0.0) - v * n
            return n

    def release_many(self, groups) -> None:
        """Release a batch of completions' demands under ONE lock
        acquisition and ONE notify — the drain-side sibling of
        :meth:`try_acquire_many`. ``groups`` is an iterable of
        ``(demand, count)`` pairs (same-shape completions pre-grouped
        by the caller); per-task release paid a lock round-trip plus a
        notify_all — and therefore a dispatch-thread wakeup — per
        completed task."""
        with self._cond:
            for demand, count in groups:
                for k, v in demand.items():
                    self._available[k] = min(
                        self._available.get(k, 0.0) + v * count,
                        self.total.get(k, 0.0))
            self._cond.notify_all()
        self._fire_on_change()


class _DirectOp:
    """Closure queued on an ActorExecutor by a compiled DAG.

    ``on_dead(cause)`` is invoked when the actor dies with the op still
    queued, so the DAG's channel fails promptly instead of timing out.
    """

    __slots__ = ("fn", "on_dead")

    def __init__(self, fn: Callable[[Any], None],
                 on_dead: Optional[Callable[[str], None]] = None):
        self.fn = fn
        self.on_dead = on_dead


class ActorExecutor:
    """Executes one actor's tasks: FIFO by seqno, optional concurrency/async.

    Reference: ``core_worker/transport/actor_scheduling_queue.h`` (ordered),
    ``out_of_order_actor_scheduling_queue.h`` (threaded/async actors), and
    the fiber-based async path (``core_worker/fiber.h``).
    """

    def __init__(self, actor_id: ActorID, max_concurrency: int,
                 run_task: Callable[[TaskSpec, Any], None],
                 run_task_async: Optional[Callable] = None,
                 concurrency_groups: Optional[Dict[str, int]] = None):
        self.actor_id = actor_id
        self.max_concurrency = max(1, max_concurrency)
        self._run_task = run_task
        self._run_task_async = run_task_async
        self.instance: Any = None
        self.is_async = False
        # Concurrency groups (reference: concurrency_group_manager.h:37):
        # each named group gets its own queue + thread pool; methods route
        # by spec.concurrency_group, "" = the default group.
        self._groups: Dict[str, Dict[str, Any]] = {}
        for name, limit in {"": self.max_concurrency,
                            **(concurrency_groups or {})}.items():
            self._groups[name] = {"heap": [], "limit": max(1, int(limit))}
        self._cond = threading.Condition()
        self._push_seq = 0
        self._dead = False
        self.death_cause: Optional[str] = None
        self._threads: List[threading.Thread] = []
        self._loop = None  # asyncio loop for async actors
        self.num_pending = 0

    def start(self, instance: Any, is_async: bool) -> None:
        self.instance = instance
        self.is_async = is_async
        if is_async:
            t = threading.Thread(target=self._async_main, daemon=True,
                                 name=f"actor-{self.actor_id.hex()[:8]}-loop")
            t.start()
            self._threads.append(t)
        else:
            for gname, group in self._groups.items():
                for i in range(group["limit"]):
                    t = threading.Thread(
                        target=self._sync_main, args=(gname,), daemon=True,
                        name=(f"actor-{self.actor_id.hex()[:8]}"
                              f"-{gname or 'default'}-{i}"))
                    t.start()
                    self._threads.append(t)

    def _group_of(self, spec: TaskSpec) -> str:
        name = getattr(spec, "concurrency_group", "") or ""
        return name if name in self._groups else ""

    def submit_direct(self, fn: Callable[[Any], None],
                      on_dead: Optional[Callable[[str], None]] = None
                      ) -> bool:
        """Compiled-graph channel op (reference: the per-actor exec loop
        of ``compiled_dag_node.py:809``): run ``fn(instance)`` on this
        actor's executor thread, FIFO-ordered with normal method calls,
        WITHOUT the task-submission machinery (no TaskSpec, scheduler,
        futures, or refcounting on the per-call path)."""
        from ray_tpu._private.ids import next_seqno
        with self._cond:
            if self._dead or self.is_async:
                return False
            self._push_seq += 1
            heapq.heappush(self._groups[""]["heap"],
                           (next_seqno(), self._push_seq,
                            _DirectOp(fn, on_dead)))
            self.num_pending += 1
            self._cond.notify_all()
        return True

    def submit(self, spec: TaskSpec) -> bool:
        with self._cond:
            if self._dead:
                return False
            # tiebreaker: seqnos from DIFFERENT submitter processes can
            # collide, and TaskSpec is not orderable
            self._push_seq += 1
            heapq.heappush(self._groups[self._group_of(spec)]["heap"],
                           (spec.seqno, self._push_seq, spec))
            self.num_pending += 1
            self._cond.notify_all()
        return True

    def kill(self, cause: str) -> List[TaskSpec]:
        """Mark dead; return tasks that were still pending."""
        with self._cond:
            if self._dead:
                return []
            self._dead = True
            self.death_cause = cause
            dropped = [spec for g in self._groups.values()
                       for _, _, spec in g["heap"]]
            pending = [s for s in dropped if not isinstance(s, _DirectOp)]
            direct_ops = [s for s in dropped if isinstance(s, _DirectOp)]
            for g in self._groups.values():
                g["heap"].clear()
            self.num_pending = 0
            self._cond.notify_all()
        for op in direct_ops:   # fail compiled-DAG channels promptly
            if op.on_dead is not None:
                try:
                    op.on_dead(cause)
                except Exception:
                    pass
        if self._loop is not None:
            try:
                self._loop.call_soon_threadsafe(self._loop.stop)
            except RuntimeError:
                pass
        return pending

    def _next(self, group: str = "") -> Optional[TaskSpec]:
        heap = self._groups[group]["heap"]
        with self._cond:
            while not heap and not self._dead:
                self._cond.wait()
            if self._dead:
                return None
            _, _, spec = heapq.heappop(heap)
            self.num_pending -= 1
            return spec

    def _next_any(self) -> Optional[TaskSpec]:
        """Async actors: one pump across all groups (semaphores bound
        per-group concurrency there)."""
        with self._cond:
            while not self._dead:
                for g in self._groups.values():
                    if g["heap"]:
                        _, _, spec = heapq.heappop(g["heap"])
                        self.num_pending -= 1
                        return spec
                self._cond.wait()
            return None

    def _sync_main(self, group: str = "") -> None:
        while True:
            spec = self._next(group)
            if spec is None:
                return
            if isinstance(spec, _DirectOp):
                try:
                    spec.fn(self.instance)
                except Exception:   # op delivers errors via its channel
                    pass
                continue
            self._run_task(spec, self.instance)

    def _async_main(self) -> None:
        import asyncio

        loop = asyncio.new_event_loop()
        self._loop = loop
        asyncio.set_event_loop(loop)
        sems = {name: asyncio.Semaphore(g["limit"])
                for name, g in self._groups.items()}

        # asyncio holds only weak references to tasks: an unretained
        # handle() task can be garbage-collected mid-await, silently
        # dropping the actor call — keep strong refs until done
        inflight: set = set()

        def track(task):  #: loop-only
            inflight.add(task)
            task.add_done_callback(inflight.discard)

        async def handle(spec):
            async with sems[self._group_of(spec)]:
                await self._run_task_async(spec, self.instance)

        async def pump():
            while True:
                spec = await loop.run_in_executor(None, self._next_any)
                if spec is None:
                    loop.stop()
                    return
                track(loop.create_task(handle(spec)))

        # the local binding retains the pump task for the whole
        # run_forever below (track() is loop-only; this thread isn't)
        pump_task = loop.create_task(pump())
        try:
            loop.run_forever()
        finally:
            for task in asyncio.all_tasks(loop):
                task.cancel()
            # Let cancellations unwind before closing the loop.
            loop.run_until_complete(
                asyncio.gather(*asyncio.all_tasks(loop),
                               return_exceptions=True))
            loop.close()


class _ExecPool:
    """Sized task-execution pool fed by the dispatch loop.

    Replaces the per-task ``_launch`` closure + semaphore feeding the
    shared ``DaemonThreadPool``: the dispatch loop hands whole admitted
    batches over in ONE lock acquisition + wakeup (``_launch`` paid a
    semaphore acquire, a pool submit, and a closure allocation per
    task), it never blocks on a full pool (the semaphore stalled it at
    capacity), and admitted-but-unstarted specs stay visible as
    TaskSpecs (``steal_pending``) so a graceful drain hands them back
    to the scheduler instead of burning them down locally (the closure
    queue made admitted work opaque and unreclaimable). Kept separate
    from ``DaemonThreadPool`` on purpose: that pool's contract is
    fire-and-forget opaque closures for its other consumers; this one
    needs a drainable, stoppable typed-spec queue."""

    def __init__(self, size: int, run_spec: Callable[[TaskSpec], None],
                 name: str):
        self._run_spec = run_spec
        self._size = max(1, size)
        self._name = name
        self._cv = threading.Condition()
        self._q: deque = deque()    #: guarded by self._cv
        self._spawned = 0           #: guarded by self._cv
        self._idle = 0              #: guarded by self._cv
        self._stopped = False       #: guarded by self._cv

    def submit_batch(self, specs) -> None:
        with self._cv:
            self._q.extend(specs)
            # spawn only to cover queued work not already matched by an
            # idle worker; stale counters over-spawn (bounded by _size),
            # never under-spawn
            spawn = min(len(self._q) - self._idle,
                        self._size - self._spawned)
            spawn = max(0, spawn)
            self._spawned += spawn
            base = self._spawned
            self._cv.notify(len(specs))
        for i in range(spawn):
            threading.Thread(target=self._work, daemon=True,
                             name=f"{self._name}-{base - i}").start()

    def steal_pending(self) -> List[TaskSpec]:
        """Atomically take every admitted-but-unstarted spec (drain
        handback / node shutdown). In-flight specs are untouched — they
        finish on their worker threads."""
        with self._cv:
            out = list(self._q)
            self._q.clear()
        return out

    def has_handback_pending(self) -> bool:
        """Any queued spec the drain pass could still hand back?
        Bounced-back specs (scheduler found nowhere else) stay here and
        run locally — without this filter the drain pass would steal
        and requeue them every dispatch tick until a thread freed up."""
        with self._cv:
            return any(not getattr(s, "_drain_bounced", False)
                       for s in self._q)

    def stop(self) -> None:
        with self._cv:
            self._stopped = True
            self._cv.notify_all()

    def _work(self) -> None:
        try:
            while True:
                with self._cv:
                    self._idle += 1
                    while not self._q and not self._stopped:
                        self._cv.wait()
                    self._idle -= 1
                    if not self._q:
                        return      # stopped and drained
                    spec = self._q.popleft()
                try:
                    self._run_spec(spec)
                except BaseException:   # noqa: BLE001 — task errors are
                    # delivered through the runtime's finish paths; a
                    # stray escape must not kill a pool worker
                    pass
        finally:
            with self._cv:
                self._spawned -= 1


def _bucket_job(key: tuple) -> str:
    """Job hex of a backlog bucket key. Tenancy-keyed buckets are
    ``(job_hex, shape_tuple)``; plain ones are the shape tuple itself
    (possible transiently around an enablement toggle) and attribute
    to the anonymous driver job."""
    return key[0] if (len(key) == 2 and isinstance(key[0], str)) else ""


class Node:
    """One (virtual) node: resources + store + dispatch loop + actors."""

    def __init__(self, node_id: NodeID, resources: Dict[str, float],
                 labels: Dict[str, str], store: LocalObjectStore,
                 execute_task: Callable[[TaskSpec, "Node"], None],
                 max_worker_threads: int = 256):
        self.node_id = node_id
        self.ledger = ResourceLedger(resources)
        self.labels = dict(labels)
        self.store = store
        self._execute_task = execute_task
        self.alive = True
        # Optional dep-staging hook (daemon-backed nodes): called at
        # enqueue so a proactive object push overlaps the task's queue
        # wait (reference: ObjectManager::Push ahead of task-arg pulls).
        self.prefetch: Optional[Callable[[TaskSpec], None]] = None
        # Multi-tenant fair share (set by the runtime when the
        # ``fairshare`` flag is on): backlog buckets become
        # (job, shape)-keyed, admission runs in deficit order under
        # per-job quota gates. None keeps this dispatch path identical
        # to the single-tenant one.
        self.tenancy = None
        # last per-job backlog counts pushed to the tenancy ledger —
        # dispatch-loop only; lets unchanged rounds skip the call
        self._tenancy_qcounts: Dict[str, int] = {}
        # Graceful drain: alive + draining = finish running work, take
        # no new placements; the dispatch loop hands queued-but-
        # unstarted tasks back to the runtime for resubmission elsewhere.
        self.draining = False
        # Node memory-pressure level ("ok"/"soft"/"hard"), mirrored
        # from daemon node_pressure pushes; pick_node soft-excludes
        # "hard" nodes the way it soft-excludes DRAINING ones.
        self.pressure_level = "ok"
        self.actors: Dict[ActorID, ActorExecutor] = {}  #: guarded by self._actors_lock
        self._actors_lock = tracked_lock("node.actors", reentrant=False)
        self._queue: "queue.Queue[Optional[TaskSpec]]" = queue.Queue()
        # Backlog bucketed by exact resource shape: one dispatch pass
        # is O(#shapes), not O(#queued tasks) — with a deep uniform
        # backlog (the reference's 1M+ queued-task envelope) a flat
        # list degrades quadratically (every completion rescans every
        # queued task). FIFO order holds within a shape; across shapes
        # there is no ordering contract (the flat scan also launched
        # whichever task fit first).
        self._backlog: "OrderedDict[tuple, deque]" = OrderedDict()
        self._backlog_n = 0
        # Demand of enqueued-but-not-yet-admitted tasks; lets the cluster
        # scheduler see load before the dispatch loop acquires resources
        # (reference: ReportWorkerBacklog, node_manager.proto:421).
        self._pending_demand: Dict[str, float] = {}  #: guarded by self._pending_lock
        self._pending_lock = tracked_lock("node.pending_demand",
                                          reentrant=False)
        self._running: set = set()      #: guarded by self._running_lock
        self._running_lock = tracked_lock("node.running", reentrant=False)
        # Coalesced ledger-release staging (flat combining): completing
        # tasks append here; whichever thread finds no flush in
        # progress drains the whole batch with ONE release_many call.
        # Uncontended completions flush inline (no added latency);
        # under a drain storm hundreds of releases share one ledger
        # lock acquisition and one dispatch-thread wakeup.
        self._release_stage: List[Dict[str, float]] = []  #: guarded by self._stage_lock
        self._stage_flushing = False    #: guarded by self._stage_lock
        self._stage_lock = tracked_lock("node.release_stage",
                                        reentrant=False)
        from ray_tpu._private.config import cfg
        pool_size = int(cfg().exec_pool_size) or max_worker_threads
        self._exec_pool = _ExecPool(pool_size, self._run_spec,
                                    name=f"task-{node_id.hex()[:8]}")
        # Event-loop instrumentation (reference: asio
        # instrumented_io_context / event_stats.h — per-handler counts and
        # queue lag surfaced in debug_state dumps).
        self.loop_stats = {"dispatch_iterations": 0, "tasks_launched": 0,
                           "max_queue_lag_ms": 0.0, "launch_ms_total": 0.0}
        # async core: the dispatch pass is a callback on the process
        # event loop — submit, release and dispatch share one thread,
        # so the cross-thread convoys (queue.Queue futex wake per
        # enqueue, ledger condition notify per completion, dispatch
        # thread wakeup per release) disappear. Producers stage on
        # plain deques and arm ONE call_soon_threadsafe per burst
        # behind a dirty flag. Threaded core: the dedicated dispatcher
        # thread below, unchanged.
        if cfg().async_core:
            from ray_tpu._private import eventloop
            self._aloop = eventloop.get_loop()
            self._inbox: deque = deque()     # GIL-atomic append/popleft
            self._wake_armed = False         # dirty flag (benign races)
            self._stopped = False            #: loop-only
            self._retry_timer = None         #: loop-only
            self._dispatcher = None
            self.ledger.on_change = self._wake_loop
        else:
            self._aloop = None
            self._dispatcher = threading.Thread(
                target=self._dispatch_loop, daemon=True,
                name=f"dispatch-{node_id.hex()[:8]}")
            self._dispatcher.start()

    def info(self) -> NodeInfo:
        return NodeInfo(node_id=self.node_id, alive=self.alive,
                        resources=dict(self.ledger.total),
                        labels=dict(self.labels))

    # -- normal task path --------------------------------------------------
    def enqueue(self, spec: TaskSpec) -> None:
        spec.enqueued_at = time.perf_counter()
        if self.prefetch is not None and spec.dependencies():
            # stage remote deps toward this node while the task waits
            # for admission (cheap no-op when every dep is local)
            try:
                self.prefetch(spec)
            except Exception:
                pass    # staging is best-effort; pulls cover misses
        with self._pending_lock:
            for k, v in spec.resources.items():
                self._pending_demand[k] = self._pending_demand.get(k, 0.0) + v
        self._post(spec)

    def _post(self, item) -> None:
        """Dispatch-input hand-off. Threaded core: the blocking queue
        (one futex wake per item). Async core: stage on a plain deque
        and coalesce wakes behind the dirty flag — one
        call_soon_threadsafe per BURST of submissions, not one per
        task."""
        if self._aloop is None:
            self._queue.put(item)
            return
        self._inbox.append(item)
        self._wake_loop()

    def _wake_loop(self) -> None:
        # benign race on the flag: two producers may both arm — the
        # second pass finds empty stages and returns; a producer that
        # loses the other way (flag already True) is covered by the
        # armed pass, which drains AFTER clearing the flag
        if self._wake_armed or self._aloop is None:
            return
        self._wake_armed = True
        try:
            self._aloop.call_soon_threadsafe(self._loop_pass)
        except RuntimeError:
            pass    # loop torn down (interpreter exit)

    def _drop_pending(self, spec: TaskSpec) -> None:
        self._drop_pending_many((spec,))

    def _drop_pending_many(self, specs) -> None:
        """One pending-lock round-trip for a whole admitted batch."""
        with self._pending_lock:
            for spec in specs:
                for k, v in spec.resources.items():
                    left = max(self._pending_demand.get(k, 0.0) - v, 0.0)
                    if left <= 1e-12:
                        # Drop zeroed keys: PG-scoped names are unique per
                        # group and would otherwise accumulate forever.
                        self._pending_demand.pop(k, None)
                    else:
                        self._pending_demand[k] = left

    def effective_available(self) -> Dict[str, float]:
        """Available capacity minus demand already queued here."""
        avail = self.ledger.available()
        with self._pending_lock:
            for k, v in self._pending_demand.items():
                avail[k] = avail.get(k, 0.0) - v
        return avail

    def _dispatch_loop(self) -> None:
        while True:
            # Move newly queued tasks into the backlog buckets.
            try:
                timeout = 0.0 if self._backlog_n else _DISPATCH_POLL_S
                while True:
                    spec = self._queue.get(timeout=timeout)
                    if spec is None:
                        return
                    if spec is _WAKE:
                        timeout = 0.0
                        continue
                    self._ingest(spec)
                    timeout = 0.0
            except queue.Empty:
                pass
            progressed = self._dispatch_pass()
            if self._backlog_n and not progressed:
                self.ledger.wait_for_change(0.05)

    def _ingest(self, spec: TaskSpec) -> None:
        """Bucket one submitted spec into the backlog (dispatch thread
        or event loop — whichever owns the backlog in this mode)."""
        # re-read per spec: the runtime attaches the tenancy manager
        # right after construction, but the dispatcher may have
        # captured a stale None before the first enqueue
        ten = self.tenancy
        key = tuple(sorted(spec.resources.items()))
        if ten is not None:
            key = (spec.job_id.hex()
                   if spec.job_id is not None else "", key)
        bucket = self._backlog.get(key)
        if bucket is None:
            bucket = self._backlog[key] = deque()
        bucket.append(spec)
        self._backlog_n += 1

    def _loop_pass(self) -> None:  #: loop-only
        """One dispatch round on the process event loop (async core).

        Producers (submit handlers, completing workers, ledger
        releases) stage work on plain deques and arm at most one of
        these per burst via ``_wake_armed``. The flag is cleared FIRST:
        a wake staged after the clear schedules a fresh pass, one
        staged before it is drained below — the occasional extra no-op
        pass (an on-loop ledger release re-arms mid-pass) is cheaper
        than a lost wake.
        """
        self._wake_armed = False
        if self._retry_timer is not None:
            self._retry_timer.cancel()
            self._retry_timer = None
        # coalesced ledger releases: one release_many for the burst
        with self._stage_lock:
            batch, self._release_stage = self._release_stage, []
        if batch:
            self._release_batch(batch)
        inbox = self._inbox
        while inbox:
            item = inbox.popleft()
            if item is None:
                self._stopped = True
                return
            if item is _WAKE:
                continue
            self._ingest(item)
        if self._stopped:
            return
        progressed = self._dispatch_pass()
        if self._backlog_n and not progressed and not self._stopped:
            # blocked on resources/quota with no release in flight —
            # poll-retry, mirroring the threaded loop's
            # wait_for_change(0.05); a real release cancels this timer
            # via the ledger's on_change wake
            self._retry_timer = self._aloop.call_later(
                0.05, self._retry_pass)

    def _retry_pass(self) -> None:  #: loop-only
        self._retry_timer = None
        self._loop_pass()

    def _dispatch_pass(self) -> bool:
        """One admission pass over the backlog buckets (shared by the
        threaded dispatcher and the loop-hosted async pass). Returns
        whether any bucket made progress; the caller decides how to
        wait when blocked (condition poll vs call_later retry)."""
        ten = self.tenancy
        if not self.alive:
            self._fail_backlog()
            return True     # backlog emptied: nothing to wait on
        if self.draining and (self._backlog_n
                              or self._exec_pool
                              .has_handback_pending()):
            # Hand queued-but-unstarted work back to the cluster
            # scheduler (no retry consumed) — both backlog entries
            # AND specs already admitted into the exec-pool queue
            # (the backlog can be empty while the pool still holds
            # unstarted work). Whatever bounces back (nowhere else
            # fits) falls through and dispatches here.
            self._resubmit_backlog()
        progressed = False
        self.loop_stats["dispatch_iterations"] += 1
        if ten is not None and self._backlog:
            # Deficit-ordered batch admission: a job's same-shape
            # ready group is considered whole, highest fair-share
            # deficit first (batch-DAG dispatch per 2002.07062) —
            # a light job's small groups cut ahead of a saturating
            # job's backlog instead of interleaving arbitrarily.
            keys = ten.order_buckets(
                [((_bucket_job(k), k), len(b))
                 for k, b in self._backlog.items()])
            keys = [k for _job, k in keys]
        else:
            keys = list(self._backlog)
        for key in keys:
            bucket = self._backlog.get(key)
            if bucket is None:
                continue
            while bucket:
                demand = bucket[0].resources
                want = len(bucket)
                if ten is not None:
                    # per-job hard-cap gate: a clamped group stays
                    # QUEUED in the backlog (never lost) until the
                    # job's own completions free quota headroom
                    want = ten.admit_cap(_bucket_job(key), demand,
                                         want)
                    if want <= 0:
                        break
                # Batch admission: every task in a bucket shares one
                # resource shape, so ONE ledger lock round-trip
                # admits as many as currently fit (per-task
                # try_acquire paid a lock + dict scan per task).
                n = self.ledger.try_acquire_many(demand, want)
                if n <= 0:
                    break
                admitted = [bucket.popleft() for _ in range(n)]
                self._backlog_n -= n
                self._drop_pending_many(admitted)
                t0 = time.perf_counter()
                for spec in admitted:
                    # Pairs this admission's ledger acquire with
                    # exactly one release: the worker may release
                    # early (see worker._release_task_resources) or
                    # _run_spec's `finally` does.
                    spec._resources_released = False
                    if spec.enqueued_at:
                        lag_ms = (t0 - spec.enqueued_at) * 1000
                        if lag_ms > self.loop_stats["max_queue_lag_ms"]:
                            self.loop_stats["max_queue_lag_ms"] = lag_ms
                        _metrics.note_queue_dwell(
                            "node.dispatch", lag_ms / 1000.0)
                        if getattr(spec, "trace_sampled", False):
                            # queue phase: backlog enqueue ->
                            # dispatch-loop admission. t0 is reused
                            # as the span end: zero extra clock
                            # reads on the dispatch thread.
                            from ray_tpu._private import events as _ev
                            _ev.record_phase_rt(
                                spec, "queue", lag_ms / 1000.0,
                                self.node_id.hex(),
                                start_wall=_ev.wall_at(
                                    spec.enqueued_at),
                                end_mono=t0)
                # count BEFORE the pool takes them: a task may
                # finish (and a get() observe it) before control
                # returns here
                self.loop_stats["tasks_launched"] += n
                if ten is not None:
                    ten.note_admitted(_bucket_job(key), demand, n)
                with self._running_lock:
                    self._running.update(s.task_id for s in admitted)
                # ONE handoff for the whole admitted batch; the
                # sized pool reuses threads instead of paying a
                # spawn + closure per task
                self._exec_pool.submit_batch(admitted)
                self.loop_stats["launch_ms_total"] += (
                    time.perf_counter() - t0) * 1000
                progressed = True
            if not bucket:
                self._backlog.pop(key, None)
        if ten is not None:
            counts: Dict[str, int] = {}
            for k, b in self._backlog.items():
                job = _bucket_job(k)
                counts[job] = counts.get(job, 0) + len(b)
            # unchanged since last round ⇒ the ledger already saw
            # this state (idle deficit reset included) — skip the
            # per-round lock round-trip
            if counts != self._tenancy_qcounts:
                self._tenancy_qcounts = counts
                ten.observe_queued(self.node_id.hex(), counts)
        return progressed

    def _run_spec(self, spec: TaskSpec) -> None:
        """One task's execution on an exec-pool worker thread."""
        try:
            self._execute_task(spec, self)
        finally:
            with self._running_lock:
                self._running.discard(spec.task_id)
            if (spec.kind != TaskKind.ACTOR_CREATION
                    and not getattr(spec, "_resources_released", True)):
                # Actors hold their resources for their whole lifetime;
                # the runtime releases them on actor death.
                spec._resources_released = True
                self.stage_release(spec.resources)
            ten = self.tenancy
            if ten is not None and spec.kind != TaskKind.ACTOR_CREATION:
                # per-job usage attribution (lock-free append); actor
                # creations are settled when the runtime releases the
                # actor's lifetime hold
                ten.note_done(spec.job_id.hex()
                              if spec.job_id is not None else "",
                              spec.resources)

    # -- coalesced ledger release (flat combining) -----------------------
    def stage_release(self, resources: Dict[str, float]) -> None:
        """Release ledger resources, coalescing concurrent completions:
        if another thread is already flushing, this release rides its
        drain (one ledger acquisition + one notify for the whole
        batch); otherwise this thread flushes inline — the uncontended
        single-task case keeps the old release latency.

        Async core: every release stages and the LOOP drains the whole
        batch at the top of its next pass — the completing worker
        thread never touches the ledger lock, and a drain storm
        collapses to one release_many + zero cross-thread dispatch
        wakeups (the pass it woke is already the one dispatching)."""
        if self._aloop is not None:
            with self._stage_lock:
                self._release_stage.append(resources)
            self._wake_loop()
            return
        with self._stage_lock:
            self._release_stage.append(resources)
            if self._stage_flushing:
                return      # the in-flight flusher drains us too
            self._stage_flushing = True
        self._drain_release_stage()

    def _drain_release_stage(self) -> None:
        while True:
            with self._stage_lock:
                batch = self._release_stage
                if not batch:
                    self._stage_flushing = False
                    return
                self._release_stage = []
            try:
                self._release_batch(batch)
            except BaseException:
                # never leave the flusher flag stuck: staged entries
                # appended meanwhile drain on the NEXT stage_release
                # call (it sees _stage_flushing False and flushes)
                with self._stage_lock:
                    self._stage_flushing = False
                raise

    def _release_batch(self, batch) -> None:
        if len(batch) == 1:
            self.ledger.release(batch[0])
            return
        # group same-shape demands: one release_many call covers
        # the whole batch under one ledger lock acquisition
        groups: "OrderedDict[tuple, list]" = OrderedDict()
        for res in batch:
            key = tuple(sorted(res.items()))
            entry = groups.get(key)
            if entry is None:
                groups[key] = [res, 1]
            else:
                entry[1] += 1
        self.ledger.release_many(groups.values())

    def _notify_off_loop(self, fn: Callable[[], None]) -> None:
        """Run runtime notifications off the event loop. The lost/
        drained callbacks resubmit through the scheduler and may do
        blocking RPC (AsyncClient.call raises on the loop by design),
        so a loop-hosted dispatch pass ships them to a helper thread;
        a plain caller (threaded core, shutdown path) runs inline."""
        from ray_tpu._private import eventloop
        if eventloop.on_loop():
            threading.Thread(target=fn, daemon=True,
                             name="node-notify").start()
        else:
            fn()

    def _fail_backlog(self) -> None:
        from ray_tpu._private import worker
        rt = worker.global_runtime()
        buckets, self._backlog = self._backlog, OrderedDict()
        self._backlog_n = 0
        backlog = [spec for bucket in buckets.values() for spec in bucket]
        for spec in backlog:
            self._drop_pending(spec)
        if rt is not None and backlog:
            def _notify() -> None:
                for spec in backlog:
                    rt.on_node_task_lost(spec, self)
            self._notify_off_loop(_notify)

    def start_drain(self) -> None:
        """Enter the DRAINING state: running tasks finish, the dispatch
        loop returns queued work to the runtime, the scheduler stops
        placing here. Runs on any thread; the backlog itself is only
        touched by the dispatch thread (woken via the sentinel)."""
        self.draining = True
        # DRAINING must leave cached pick_node candidate sets NOW, not
        # at the next natural invalidation
        _bump_cluster_epoch()
        self._post(_WAKE)

    def _resubmit_backlog(self) -> None:
        """Graceful-drain pass (dispatch thread only): queued tasks that
        have not been bounced before go back to the cluster scheduler;
        a task the scheduler sent BACK here (nothing else fits) keeps
        its spot and dispatches locally — no resubmit ping-pong."""
        from ray_tpu._private import worker
        rt = worker.global_runtime()
        if rt is None:
            return
        keep: "OrderedDict[tuple, deque]" = OrderedDict()
        moved: List[TaskSpec] = []
        for key, bucket in self._backlog.items():
            stay: deque = deque()
            for spec in bucket:
                if getattr(spec, "_drain_bounced", False):
                    stay.append(spec)
                else:
                    moved.append(spec)
            if stay:
                keep[key] = stay
        self._backlog = keep
        self._backlog_n = sum(len(b) for b in keep.values())
        for spec in moved:
            self._drop_pending(spec)
        handback = self._steal_drain_handback()
        drained = moved + handback
        if drained:
            def _notify() -> None:
                for spec in drained:
                    rt.on_node_task_drained(spec, self)
            self._notify_off_loop(_notify)

    def _steal_drain_handback(self) -> List[TaskSpec]:
        """Exec-pool drain interaction: in-flight tasks finish on their
        worker threads, but admitted-but-unstarted specs still sitting
        in the pool queue are stolen back and their ledger admission
        undone; the returned specs are handed to the scheduler like
        backlog entries (no retry consumed). Bounced-back specs
        (nothing else fits) re-feed the pool and run here."""
        stolen = self._exec_pool.steal_pending()
        if not stolen:
            return []
        requeue: List[TaskSpec] = []
        handback: List[TaskSpec] = []
        for spec in stolen:
            if getattr(spec, "_drain_bounced", False):
                requeue.append(spec)
            else:
                handback.append(spec)
        if requeue:
            self._exec_pool.submit_batch(requeue)
        if not handback:
            return []
        with self._running_lock:
            for spec in handback:
                self._running.discard(spec.task_id)
        for spec in handback:
            # undo the admission's ledger acquire before rescheduling
            if not getattr(spec, "_resources_released", True):
                spec._resources_released = True
                self.stage_release(spec.resources)
                if self.tenancy is not None:
                    self.tenancy.note_done(
                        spec.job_id.hex()
                        if spec.job_id is not None else "",
                        spec.resources)
        return handback

    # -- actor hosting -----------------------------------------------------
    def host_actor(self, executor: ActorExecutor) -> None:
        with self._actors_lock:
            self.actors[executor.actor_id] = executor

    def evict_actor(self, actor_id: ActorID) -> None:
        with self._actors_lock:
            self.actors.pop(actor_id, None)

    # -- lifecycle ---------------------------------------------------------
    def shutdown(self, fail_tasks: bool = True) -> Dict[ActorID, List[TaskSpec]]:
        """Stop the node; returns per-actor pending tasks for FT handling."""
        self.alive = False
        _bump_cluster_epoch()
        self._post(None)
        pending_by_actor: Dict[ActorID, List[TaskSpec]] = {}
        with self._actors_lock:
            actors = dict(self.actors)
            self.actors.clear()
        for aid, ex in actors.items():
            pending_by_actor[aid] = ex.kill("node died")
        if fail_tasks:
            self._fail_backlog()
            self._fail_pool_pending()
        # let in-flight pool work unwind, then retire the idle threads
        self._exec_pool.stop()
        return pending_by_actor

    def _fail_pool_pending(self) -> None:
        """Node death with specs admitted but not yet started: route
        them through the same lost-task flow as the backlog."""
        stolen = self._exec_pool.steal_pending()
        if not stolen:
            return
        from ray_tpu._private import worker
        rt = worker.global_runtime()
        with self._running_lock:
            for spec in stolen:
                self._running.discard(spec.task_id)
        if rt is not None:
            for spec in stolen:
                rt.on_node_task_lost(spec, self)
