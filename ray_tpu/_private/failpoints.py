"""Deterministic fault injection: named failpoints on control-plane seams.

Reference capability: the C++ runtime's testing failpoints / chaos hooks
(``RAY_testing_*`` fault-injection flags and the release chaos suites)
— on real TPU fleets preemption and transient RPC loss are the norm, so
every failure path must be drivable deterministically instead of via
ad-hoc monkeypatching.

A *failpoint* is a named seam in the runtime (``"rpc.client.send"``,
``"daemon.push_task"``, ...) that calls :func:`fire` when the registry
is active. Seams cut REACTIONS as well as actions: the object-plane
reclamation seams (``"arena.grant_reclaim"``,
``"arena.reservation_sweep"``) suppress the daemon's *response* to a
client death so chaos runs can prove the backstop (heartbeat sweep,
event-path retry) still converges. An *arm* configured for that name
decides what happens:

=========== ==============================================================
action      effect at the seam
=========== ==============================================================
``crash``   ``os._exit(17)`` — the process dies (worker/daemon/head kill)
``delay``   sleep ``arg`` milliseconds, then continue
``drop``    :func:`fire` returns :data:`DROP`; the seam swallows the
            frame/message (request vanishes; the peer sees a timeout)
``error``   raise ``arg`` (an exception class; default
            :class:`FailpointError`)
``return``  :func:`fire` returns ``Return(arg)``; the seam short-circuits
            with that value
=========== ==============================================================

Each arm carries firing controls: ``p`` (probability, drawn from the
registry's seeded RNG — the same seed replays the same schedule),
``every`` (fire on every Nth hit), ``after`` (skip the first N hits) and
``max`` (stop after M fires). Every *fire* is appended to a thread-safe
hit log so tests assert exact fault counts.

Activation (all processes of a cluster see the same spec because daemon
and head processes inherit the driver's environment):

- env var ``RAY_TPU_FAILPOINTS`` (parsed at import), with
  ``RAY_TPU_FAILPOINTS_SEED`` for the RNG seed;
- the ``failpoints`` / ``failpoints_seed`` config flags (applied at
  ``ray_tpu.init``);
- programmatically: :func:`activate` / :func:`configure` / :func:`reset`.

Spec grammar (``;``-separated)::

    name=action[:mod[:mod...]]
    action  := crash | delay(<ms>) | drop | error[(<ExcName>)]
             | return[(<literal>)]
    mod     := p=<float> | every=<int> | after=<int> | max=<int>

e.g. ``RAY_TPU_FAILPOINTS='rpc.client.send=drop:every=3:max=2;``
``daemon.push_task=delay(50):p=0.2'``.

Fast path: when nothing is configured, call sites pay ONE module-global
boolean check (``if failpoints.ENABLED: ...``) — no dict lookups, no
function call.
"""

from __future__ import annotations

import ast
import os
import random
import threading
import time
from typing import Any, Dict, List, Optional

__all__ = [
    "ENABLED", "DROP", "Return", "FailpointError",
    "activate", "configure", "reset", "fire",
    "hit_count", "fire_count", "hit_log", "describe",
]

# Module-global guard rebound by activate()/reset(). Call sites read it
# as `failpoints.ENABLED` — a single module-dict lookup — before paying
# anything else.
ENABLED = False


class FailpointError(Exception):
    """Default exception injected by an ``error`` arm."""


class _Drop:
    def __repr__(self) -> str:  # pragma: no cover - repr only
        return "<failpoints.DROP>"


DROP = _Drop()


class Return:
    """``return`` action outcome: the seam short-circuits with .value."""

    __slots__ = ("value",)

    def __init__(self, value: Any = None):
        self.value = value

    def __repr__(self) -> str:  # pragma: no cover - repr only
        return f"Return({self.value!r})"


_ACTIONS = ("crash", "delay", "drop", "error", "return")


class _Arm:
    __slots__ = ("name", "action", "arg", "p", "every", "after",
                 "max_fires", "hits", "fires", "rng")

    def __init__(self, name: str, action: str, arg: Any = None,
                 p: float = 1.0, every: int = 1, after: int = 0,
                 max_fires: int = 0):
        if action not in _ACTIONS:
            raise ValueError(f"unknown failpoint action {action!r}; "
                             f"expected one of {_ACTIONS}")
        self.name = name
        self.action = action
        self.arg = arg
        self.p = float(p)
        self.every = max(1, int(every))
        self.after = max(0, int(after))
        self.max_fires = max(0, int(max_fires))
        self.hits = 0       # times fire() reached this arm
        self.fires = 0      # times the action actually ran
        self.rng = random.Random()    # re-seeded per-arm on install


def _resolve_exc(name: str):
    """Resolve an exception class by name: builtins, then the runtime's
    own error types (RpcError, FastLaneError, ...). Called at FIRE time,
    never at parse time — env activation runs during this module's own
    import, when rpc.py/fast_lane.py (which import failpoints first) are
    only partially initialized and their error classes don't exist yet."""
    import builtins
    cls = getattr(builtins, name, None)
    if isinstance(cls, type) and issubclass(cls, BaseException):
        return cls
    for mod_name in ("ray_tpu._private.rpc", "ray_tpu._private.fast_lane",
                     "ray_tpu.exceptions"):
        try:
            import importlib
            mod = importlib.import_module(mod_name)
        except Exception:       # pragma: no cover - import cycles only
            continue
        cls = getattr(mod, name, None)
        if isinstance(cls, type) and issubclass(cls, BaseException):
            return cls
    raise ValueError(f"failpoint error({name}): unknown exception class")


def _parse_action(text: str):
    """``delay(50)`` -> ("delay", 50.0); ``error(OSError)`` ->
    ("error", OSError); ``drop`` -> ("drop", None)."""
    text = text.strip()
    if "(" in text:
        head, _, rest = text.partition("(")
        inner = rest.rstrip()
        if not inner.endswith(")"):
            raise ValueError(f"malformed failpoint action {text!r}")
        inner = inner[:-1].strip()
    else:
        head, inner = text, ""
    head = head.strip()
    if head == "delay":
        return head, float(inner or 0.0)
    if head == "error":
        # keep the NAME; resolution happens lazily at fire() time (see
        # _resolve_exc) — an unknown name then raises ValueError at the
        # seam, loudly
        return head, (inner or None)
    if head == "return":
        if not inner:
            return head, None
        try:
            return head, ast.literal_eval(inner)
        except (ValueError, SyntaxError):
            return head, inner      # bare word: return it as a string
    if head in ("crash", "drop"):
        return head, None
    raise ValueError(f"unknown failpoint action {head!r}")


def parse_spec(spec: str) -> List[_Arm]:
    arms: List[_Arm] = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        name, sep, rhs = part.partition("=")
        if not sep:
            raise ValueError(f"malformed failpoint {part!r} "
                             f"(expected name=action[:mods])")
        # split modifiers on ':' outside parentheses (a literal in
        # return(...) may contain anything)
        pieces: List[str] = []
        depth = 0
        cur = ""
        for ch in rhs:
            if ch == ":" and depth == 0:
                pieces.append(cur)
                cur = ""
                continue
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
            cur += ch
        pieces.append(cur)
        action, arg = _parse_action(pieces[0])
        kw: Dict[str, Any] = {}
        for mod in pieces[1:]:
            k, _, v = mod.partition("=")
            k = k.strip()
            if k == "p":
                kw["p"] = float(v)
            elif k == "every":
                kw["every"] = int(v)
            elif k == "after":
                kw["after"] = int(v)
            elif k == "max":
                kw["max_fires"] = int(v)
            else:
                raise ValueError(f"unknown failpoint modifier {k!r}")
        arms.append(_Arm(name.strip(), action, arg, **kw))
    return arms


class Registry:
    """Seed-driven failpoint registry with a thread-safe hit log."""

    def __init__(self, seed: Optional[int] = None):
        self._arms: Dict[str, _Arm] = {}
        self._log: List[Dict[str, Any]] = []
        self._lock = threading.Lock()
        self.seed = seed

    def install(self, arm: _Arm) -> None:
        # per-arm RNG derived from (seed, name): probability draws of
        # one arm can't perturb another's, so the same seed replays the
        # same per-seam schedule even when hits from different seams
        # (or threads on other seams) interleave differently
        if self.seed is not None:
            arm.rng = random.Random(f"{self.seed}:{arm.name}")
        with self._lock:
            self._arms[arm.name] = arm

    def remove(self, name: str) -> None:
        with self._lock:
            self._arms.pop(name, None)

    def active(self) -> bool:
        with self._lock:
            return bool(self._arms)

    def fire(self, name: str, **ctx) -> Any:
        arm = self._arms.get(name)
        if arm is None:
            return None
        with self._lock:
            arm.hits += 1
            if arm.hits <= arm.after:
                return None
            if (arm.hits - arm.after) % arm.every != 0:
                return None
            if arm.max_fires and arm.fires >= arm.max_fires:
                return None
            if arm.p < 1.0 and arm.rng.random() >= arm.p:
                return None
            arm.fires += 1
            action, arg = arm.action, arm.arg
            entry = {"name": name, "action": action, "hit": arm.hits,
                     "fire": arm.fires, "ts": time.time()}
            if ctx:
                entry.update(ctx)
            self._log.append(entry)
        # effects run OUTSIDE the lock: delay must not serialize every
        # other failpoint behind it, and error/crash must not leak a
        # held lock into the unwound stack
        if action == "delay":
            time.sleep(arg / 1000.0)
            return None
        if action == "crash":
            os._exit(17)
        if action == "error":
            if arg is None:
                exc_cls = FailpointError
            elif isinstance(arg, str):
                exc_cls = _resolve_exc(arg)
                with self._lock:    # cache the resolved class
                    arm.arg = exc_cls
            else:
                exc_cls = arg
            raise exc_cls(f"injected by failpoint {name!r}")
        if action == "drop":
            return DROP
        if action == "return":
            return Return(arg)
        return None     # pragma: no cover - _ACTIONS is exhaustive

    # -- introspection (test assertions) --------------------------------
    def hit_count(self, name: str) -> int:
        with self._lock:
            arm = self._arms.get(name)
            return arm.hits if arm is not None else 0

    def fire_count(self, name: str) -> int:
        with self._lock:
            arm = self._arms.get(name)
            return arm.fires if arm is not None else 0

    def log(self, name: Optional[str] = None) -> List[Dict[str, Any]]:
        with self._lock:
            if name is None:
                return list(self._log)
            return [e for e in self._log if e["name"] == name]

    def describe(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {n: {"action": a.action, "arg": a.arg, "p": a.p,
                        "every": a.every, "after": a.after,
                        "max": a.max_fires, "hits": a.hits,
                        "fires": a.fires}
                    for n, a in self._arms.items()}


_registry = Registry()


def activate(spec: str = "", seed: Optional[int] = None) -> Registry:
    """Install a fresh registry from ``spec`` and enable firing. An
    empty spec still enables the registry (arms can be added with
    :func:`configure`)."""
    global _registry, ENABLED
    reg = Registry(seed)
    for arm in parse_spec(spec):
        reg.install(arm)
    _registry = reg
    ENABLED = True
    return reg


def configure(name: str, action: str, arg: Any = None, p: float = 1.0,
              every: int = 1, after: int = 0, max_fires: int = 0) -> None:
    """Add/replace one failpoint arm programmatically (enables the
    registry if needed)."""
    global ENABLED
    _registry.install(_Arm(name, action, arg, p=p, every=every,
                           after=after, max_fires=max_fires))
    ENABLED = True


def remove(name: str) -> None:
    _registry.remove(name)


def reset() -> None:
    """Deactivate: every seam goes back to the one-boolean no-op path.
    Also clears the env form so later-spawned processes start clean."""
    global _registry, ENABLED
    ENABLED = False
    _registry = Registry()
    os.environ.pop("RAY_TPU_FAILPOINTS", None)
    os.environ.pop("RAY_TPU_FAILPOINTS_SEED", None)


def fire(name: str, **ctx) -> Any:
    """Evaluate the failpoint ``name``. Returns None (no-op), DROP, or a
    Return — after applying crash/delay/error effects. Call sites guard
    with ``if failpoints.ENABLED:`` so the inactive path stays free."""
    return _registry.fire(name, **ctx)


def hit_count(name: str) -> int:
    return _registry.hit_count(name)


def fire_count(name: str) -> int:
    return _registry.fire_count(name)


def hit_log(name: Optional[str] = None) -> List[Dict[str, Any]]:
    return _registry.log(name)


def describe() -> Dict[str, Dict[str, Any]]:
    return _registry.describe()


def maybe_activate_from_config(cfg) -> None:
    """``ray_tpu.init`` hook: the ``failpoints`` flag activates the
    registry for this process AND exports the env form so processes
    spawned later (daemons, head, workers — ``_spawn`` copies
    ``os.environ``) replay the same spec; without the export, the
    daemon/head seams would silently never fire."""
    spec = getattr(cfg, "failpoints", "")
    if not spec or ENABLED:
        return
    seed = int(getattr(cfg, "failpoints_seed", 0) or 0)
    os.environ["RAY_TPU_FAILPOINTS"] = spec
    if seed:
        os.environ["RAY_TPU_FAILPOINTS_SEED"] = str(seed)
    activate(spec, seed=seed or None)


# env activation: daemons/head/workers are spawned with the driver's
# environment, so one export drives the whole cluster deterministically
_env_spec = os.environ.get("RAY_TPU_FAILPOINTS", "")
if _env_spec:
    activate(_env_spec,
             seed=int(os.environ.get("RAY_TPU_FAILPOINTS_SEED", "0")
                      or 0) or None)
del _env_spec
