"""Node daemon process (the raylet equivalent).

Reference capability: the per-node daemon of
``src/ray/raylet/node_manager.cc`` — worker-lease protocol
(``HandleRequestWorkerLease`` :1754), a pool of real worker processes
(``worker_pool.h``), placement-group bundle 2PC
(``node_manager.proto:443-452``), and the node's object plane (plasma
store + ``object_manager.cc:247,354`` pull/push). Spawned as its own OS
process (``python -m ray_tpu._private.daemon``); all traffic is typed
msgpack RPC (:mod:`ray_tpu._private.rpc`).

Division of labor (TPU-first): the daemon executes HOST-plane work only —
its workers are CPU-pinned processes (forkserver pool reused from
:mod:`worker_process`). Accelerator work never lands here; it stays in
the mesh-owning driver. The daemon never unpickles user payloads (raw
blobs in, raw blobs out, like the real raylet): user code exists only in
its worker processes.

Object plane: results too big to inline live in the daemon's object
table — small ones in a dict, large ones in the C++ shm arena
(``native/shm_store.cc``) — and are served by (a) raw-bytes RPC, (b)
same-host zero-copy: ``get_object`` replies (arena name, offset, size)
with a pinned ref; the client attaches the arena by name and reads the
range directly (plasma's fd-passing role), then releases; (c)
daemon⇄daemon ``pull_object`` for inter-node transfer.

Worker-initiated core ops (nested ``ray_tpu.*`` inside tasks) forward
raw to the OWNER (driver) over a dedicated connection — the
CoreWorkerService direction of the reference.
"""

from __future__ import annotations

import argparse
import json
import os
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, Optional, Tuple

import cloudpickle

from ray_tpu._private import events as _events
from ray_tpu._private import eventloop
from ray_tpu._private import failpoints as _fp
from ray_tpu._private import rpc
from ray_tpu._private.head import HeadClient, _hb_interval
from ray_tpu._private.ids import ActorID, NodeID, TaskID
from ray_tpu._private.lock_sanitizer import tracked_lock
from ray_tpu._private.task_spec import TaskKind, TaskSpec
from ray_tpu._private.rpc import Client, Connection, Server, declare
from ray_tpu.util import metrics as _metrics
from ray_tpu.util import profiling as _profiling

INLINE_RESULT = 100 * 1024  # reference: max_direct_call_object_size

declare("hello_driver", "owner_addr", "job_id", "namespace", "sys_path")
declare("request_worker_lease", "task_meta")
declare("return_worker", "lease_id")
declare("push_task", "spec", "fid", "args", "lease_id", "backpressure")
declare("submit_task", "spec", "fid", "args", "backpressure")
# coalesced submit: many tasks per frame; `fns` ships each function blob
# once per (daemon, fid); completions return batched on task_batch_done
# push frames. Retried frames dedupe by task id (idempotent).
declare("push_task_batch", "tasks", "fns")
declare("create_actor", "spec", "fid", "args")
declare("call_actor_method", "spec", "args")
declare("kill_actor", "actor_id", "expected")
declare("cancel_task", "task_id", "force")
declare("gen_ack", "task_id")
declare("prepare_bundle", "pg_id", "index", "resources")
declare("commit_bundle", "pg_id", "index")
declare("cancel_bundle", "pg_id", "index")
declare("put_object", "oid", "blob")
declare("get_object", "oid", "prefer_shm")
declare("object_meta", "oid")
declare("get_object_chunk", "oid", "off", "size")
declare("release_object", "oid")
declare("free_objects", "oids")
declare("pull_object", "oid", "from_addr", "priority")
# zero-copy object plane (docs/object_plane.md): reserve+seal let a
# same-host client write the payload straight into the arena (only
# metadata rides the wire); push_object/push_chunk are the proactive
# daemon->daemon transfer direction (PushManager)
declare("create_object", "oid", "size")
declare("seal_object", "oid", "ref", "raw", "nbytes")
declare("push_object", "oid", "to_addr", "ref")
declare("push_chunk", "oid", "off", "total", "blob", "ref", "raw")
declare("daemon_ping")
# fair-share federation: the driver mirrors its per-job quota/weight
# table here (capability-gated on the "tenancy" hello bit)
declare("tenancy_sync", "jobs")
# cross-language tier (C++ clients): names resolve through the head KV,
# args/results are plain msgpack values — no Python pickles cross the
# language boundary (reference: ray cross_language function descriptors)
declare("xlang_submit", "name", "args")
declare("xlang_create_actor", "cls", "name", "args")
declare("xlang_call_actor", "name", "method", "args")
declare("daemon_stop")
declare("daemon_stats")
# on-demand profiling burst: the daemon samples its own stacks AND fans
# out to its live pool workers; blocks ~duration (handler is
# @concurrent so it cannot head-of-line-block the connection lane)
declare("profile_burst", "duration")
declare("syncer_exchange", "view")
declare("syncer_view")
declare("oom_check", "task_id", "fast_lane")
declare("set_memory_limit", "limit")
declare("core_op", "call", "payload", "task")
declare("core_release", "task")
# chaos harness only: (de)activate a seeded network-chaos spec inside
# THIS daemon process — lets a campaign partition one node's head link
# when env activation (pre-spawn, all nodes) is too blunt
declare("net_chaos", "spec")
# same per-node chaos hook for failpoints: arm a seeded spec inside
# THIS daemon process (e.g. pressure.level on one node) when env
# activation — which reaches every spawned process — is too blunt
declare("fail_points", "spec")


# ---------------------------------------------------------------------------
# preemption watcher: self-announced graceful drain
# ---------------------------------------------------------------------------

class PreemptionWatcher:
    """Funnels preemption/maintenance notices into ONE self-announced
    graceful drain to the head (reference: spot TPU-VM preemption — the
    ACPI SIGTERM plus the metadata server's maintenance-event endpoint).

    Sources, all converging on :meth:`notify`:

    - **SIGTERM** — ``install_sigterm()`` (daemon ``main()`` installs it
      before entering the heartbeat loop; the handler only sets an
      event, the announce RPC runs on the watcher thread);
    - **notice file** — ``drain_notice_file`` flag: the file appearing
      is the notice, its content the reason (the pluggable, air-gapped
      stand-in for polling the cloud metadata server);
    - **programmatic** — ``notify(reason)`` from any integration hook.

    After announcing, the daemon keeps serving: the head's DRAINING
    state fences new placements, the driver migrates work off, and the
    head escalates to the death path at the deadline — at which point
    the heartbeat's ``{"dead": True}`` reply makes this process exit.
    """

    def __init__(self, node_id_hex: str, head_addr: Tuple[str, int],
                 deadline_s: float, notice_file: str = ""):
        self.node_id_hex = node_id_hex
        self.head_addr = head_addr
        self.deadline_s = deadline_s
        self.notice_file = notice_file
        self.announced = False
        self._reason = "preemption"
        self._event = threading.Event()

    def notify(self, reason: str = "preemption") -> None:
        self._reason = reason
        self._event.set()

    def install_sigterm(self) -> None:
        import signal

        def handler(signum, frame):
            self.notify("sigterm")

        try:
            signal.signal(signal.SIGTERM, handler)
        except ValueError:
            pass    # not the main thread (embedded use): file/hook only

    def start(self) -> None:
        threading.Thread(target=self._loop, daemon=True,
                         name="preemption-watch").start()

    def _loop(self) -> None:
        while not self._event.wait(0.2):
            if self.notice_file and os.path.exists(self.notice_file):
                try:
                    with open(self.notice_file) as fh:
                        reason = fh.read().strip() or "maintenance notice"
                except OSError:
                    reason = "maintenance notice"
                self.notify(reason)
        self._announce()

    def _announce(self) -> None:
        if self.announced:
            return
        self.announced = True
        if _fp.ENABLED:
            try:
                # drop/error arm = the notice never reaches the head
                # (the VM then just dies: the ordinary crash path is
                # the backstop); delay arm shrinks the drain window
                if _fp.fire("drain.announce",
                            node=self.node_id_hex) is _fp.DROP:
                    return
            except Exception:
                return
        try:
            head = HeadClient(self.head_addr)
            try:
                head.drain_node(self.node_id_hex, self.deadline_s,
                                self._reason)
            finally:
                head.close()
        except (OSError, rpc.RpcError):
            pass    # head unreachable: crash-path recovery covers us


# ---------------------------------------------------------------------------
# object table: dict for small blobs, C++ shm arena for large ones
# ---------------------------------------------------------------------------

# Ledger identity for grants whose caller could not be established
# (legacy callers, direct in-process use). Never swept by liveness —
# reclaimed only when refs observably hit zero.
UNKNOWN_CLIENT = "?"


def _pid_alive(pid: int) -> bool:
    """Liveness probe for the orphan sweep (signal 0 = existence check;
    EPERM still proves the pid exists)."""
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    except OSError:
        return False
    return True


class ObjectTable:
    def __init__(self, arena_name: str, capacity: int,
                 sweep: bool = True, spill_dir: Optional[str] = None,
                 spill_budget: int = 0):
        self._small: Dict[bytes, bytes] = {}  #: guarded by self._lock
        self._lock = tracked_lock("daemon.object_table", reentrant=False)
        self.arena_name = arena_name
        self.capacity = capacity
        # logical ObjectID binary -> daemon store key: lets same-node
        # consumers (attached workers) resolve a ray_tpu ref without
        # the owner round trip (the node-local slice of the object
        # directory); raw-tier entries carry (dtype, shape) so views
        # need no unpickle at all
        self._by_oid: Dict[bytes, bytes] = {}   #: guarded by self._lock
        self._ref_of: Dict[bytes, bytes] = {}   #: guarded by self._lock
        self._raw: Dict[bytes, Any] = {}        #: guarded by self._lock
        # per-client grant ledger: every slot ref the owner increments
        # on a client's behalf (get_ext_meta) is charged to that
        # client's identity, so liveness-driven reclamation can drop a
        # dead client's outstanding grants without a daemon restart.
        # Clients release with SILENT local atomics, so a ledger count
        # is an UPPER BOUND on what the client still holds — reclaim
        # drops min(granted, observed_refs - other clients' ledger
        # counts) and the orphan sweep trues up the residue (see
        # docs/object_plane.md "crash reclamation").
        self._ext_slots: Dict[str, Dict[int, int]] = {}  #: guarded by self._lock
        # slot -> oid of the last grant (operator attribution); the
        # native lib has no slot-enumeration API, so leak observability
        # (ray_tpu_arena_slot_refs) polls ext_refs() over this set.
        self._slot_owners: Dict[int, bytes] = {}  #: guarded by self._lock
        # unsealed direct-put reservations: key -> (client_id, ts);
        # popped at seal/abort, aborted by reclaim_client and by the
        # heartbeat sweep once past the TTL.
        self._reservations: Dict[bytes, Tuple[str, float]] = {}  #: guarded by self._lock
        # -- arena spill tier (docs/object_plane.md "Arena spill") --
        # The native store has no key-enumeration API, so spill
        # candidacy needs a Python-side index of SEALED arena entries:
        # key -> nbytes in LRU order (move_to_end on every read grant).
        # None spill_dir = spilling disarmed (every op short-circuits).
        self.spill_dir = spill_dir
        self.spill_budget = int(spill_budget or 0)
        self._entries: "OrderedDict[bytes, int]" = OrderedDict()  #: guarded by self._lock
        # key -> (path, nbytes) for entries currently parked on disk
        self._spilled: Dict[bytes, Tuple[str, int]] = {}  #: guarded by self._lock
        self._spill_stats = {"spills": 0, "restores": 0,
                             "spilled_bytes": 0, "restored_bytes": 0,
                             "spill_skipped_pinned": 0,
                             "restore_failed": 0}  #: guarded by self._lock
        self._spilled_total = 0     #: guarded by self._lock
        self._shm = None
        if sweep:
            # stale-segment hygiene: a SIGKILL'd predecessor daemon of
            # this node never unlinked its arena — reap it before
            # creating ours (same name => same node)
            try:
                from ray_tpu.objectplane.arena import sweep_stale_segments
                sweep_stale_segments(arena_name)
            except Exception:
                pass
        try:
            from ray_tpu.native_store import ShmObjectStore

            self._shm = ShmObjectStore(arena_name, capacity)
        except Exception:
            self._shm = None  # g++ missing: dict-only fallback

    def put(self, oid: bytes, blob: bytes) -> None:
        if self._shm is not None and len(blob) > INLINE_RESULT:
            if self.spill_dir is not None:
                with self._lock:
                    if oid in self._spilled:
                        return  # already stored, parked on disk
            for attempt in range(2):
                try:
                    self._shm.put(oid, blob, pin=True)
                    with self._lock:
                        self._entries[oid] = len(blob)
                        self._entries.move_to_end(oid)
                    return
                except KeyError:
                    return  # already stored (idempotent retry)
                except Exception:
                    # arena full: spill cold entries once, then retry;
                    # still full (or spilling disarmed) → dict fallback
                    if attempt or not self.spill_for(len(blob)):
                        break
        with self._lock:
            self._small[oid] = blob

    def get_blob(self, oid: bytes) -> Optional[bytes]:
        with self._lock:
            blob = self._small.get(oid)
        if blob is not None:
            return blob
        if self._shm is not None:
            if not self._maybe_restore(oid):
                # restore failed (arena still full / failpoint): serve
                # the bytes straight off the spill file — a read must
                # degrade to a disk read, never to a miss
                return self._read_spilled(oid)
            try:
                view = self._shm.get_view(oid)  # increfs
                try:
                    self._touch(oid)
                    return view.tobytes()
                finally:
                    self._shm.release(oid)
            except KeyError:
                return None
        return None

    def get_shm_ref(self, oid: bytes):
        """(arena, capacity, off, size) with a held ref, or None."""
        if self._shm is None:
            return None
        self._maybe_restore(oid)
        try:
            off, size = self._shm.get_ref(oid)
        except KeyError:
            return None
        self._touch(oid)
        return (self.arena_name, self.capacity, off, size)

    def get_ext_meta(self, oid: bytes, client_id: str = UNKNOWN_CLIENT):
        """(arena, capacity, off, size, slot) with the object's
        PROCESS-SHARED slot refcount incremented on the client's behalf
        (the client reads through its own mapping and drops the ref with
        a local atomic — no release round trip), or None. The grant is
        charged to ``client_id`` in the ledger; incref + ledger entry
        commit under one lock hold so reclaim/sweep never observe a ref
        whose holder is not yet recorded."""
        if self._shm is None:
            return None
        self._maybe_restore(oid)
        with self._lock:
            try:
                off, size, slot = self._shm.get_ext(oid)
            except Exception:
                return None
            grants = self._ext_slots.setdefault(client_id, {})
            grants[slot] = grants.get(slot, 0) + 1
            self._slot_owners[slot] = oid
            if oid in self._entries:
                self._entries.move_to_end(oid)
        return (self.arena_name, self.capacity, off, size, slot)

    def ext_release(self, slot: int, client_id: Optional[str] = None
                    ) -> None:
        """Owner-side slot release (the RPC fallback path for clients
        with no local mapping). When the caller is identified, the
        ledger charge drops with the ref so reclaim never re-drops it."""
        if self._shm is None:
            return
        with self._lock:
            try:
                self._shm.ext_release(slot)
            except Exception:
                pass
            if client_id is not None:
                grants = self._ext_slots.get(client_id)
                if grants and slot in grants:
                    if grants[slot] <= 1:
                        del grants[slot]
                    else:
                        grants[slot] -= 1
                    if not grants:
                        del self._ext_slots[client_id]

    def slot_ref_stats(self, attribution: bool = False) -> Dict[str, Any]:
        """{"held": slots with outstanding external refs, "refs": total
        outstanding external refs} over every slot ever granted via
        get_ext_meta. Fully-released slots leave tracking here (their
        ledger charges are cleared too — refs hitting zero proves every
        grant was released); what remains with refs > 0 is live readers
        or a not-yet-reclaimed grant. With ``attribution`` the reply
        adds ``clients``: per-client ledger rows so operators can see
        WHO holds a slot. Zeros on the dict-only fallback."""
        if self._shm is None:
            return {"held": 0, "refs": 0, "clients": []} if attribution \
                else {"held": 0, "refs": 0}
        held = refs = 0
        with self._lock:
            tracked = set(self._slot_owners)
            for grants in self._ext_slots.values():
                tracked.update(grants)
            released = []
            for slot in tracked:
                try:
                    n = int(self._shm.ext_refs(slot))
                except Exception:
                    n = 0
                if n > 0:
                    held += 1
                    refs += n
                else:
                    released.append(slot)
            for slot in released:
                self._slot_owners.pop(slot, None)
                for cid in list(self._ext_slots):
                    grants = self._ext_slots[cid]
                    grants.pop(slot, None)
                    if not grants:
                        del self._ext_slots[cid]
            out: Dict[str, Any] = {"held": held, "refs": refs}
            if attribution:
                out["clients"] = [
                    {"client": cid,
                     "slots": len(grants),
                     "granted": sum(grants.values())}
                    for cid, grants in sorted(self._ext_slots.items())]
        return out

    def ledger_clients(self) -> list:
        """Client ids with outstanding grants or reservations (sweep
        input: the service checks each for liveness)."""
        with self._lock:
            out = set(self._ext_slots)
            out.update(cid for cid, _ts in self._reservations.values())
            return sorted(out)

    def reclaim_client(self, client_id: str) -> Tuple[int, int]:
        """Drop a dead client's outstanding state: CAS-drop its slot
        grants (bounded so a grant the client already released locally
        — or a ref another live client holds — is never stolen), abort
        its unsealed reservations, then reap so deferred deletes free
        NOW rather than at daemon restart. Returns (refs dropped,
        reservations aborted). Idempotent: a second call finds an empty
        ledger and does nothing."""
        with self._lock:
            grants = self._ext_slots.pop(client_id, None) or {}
            res_keys = [k for k, (cid, _ts) in self._reservations.items()
                        if cid == client_id]
            for k in res_keys:
                self._reservations.pop(k, None)
            dropped = 0
            if self._shm is not None and grants:
                # ledger counts of every OTHER still-registered client
                # per slot: ledgers over-count (silent local releases),
                # so observed - others is a SAFE LOWER BOUND on what the
                # dead client still holds. Residue trues up in the
                # orphan sweep once the co-holders release or die.
                others: Dict[int, int] = {}
                for grants_o in self._ext_slots.values():
                    for slot, n in grants_o.items():
                        if slot in grants:
                            others[slot] = others.get(slot, 0) + n
                for slot, granted in grants.items():
                    try:
                        observed = int(self._shm.ext_refs(slot))
                    except Exception:
                        continue
                    n = min(granted, max(0, observed - others.get(slot, 0)))
                    if n > 0:
                        try:
                            dropped += int(self._shm.ext_release_n(slot, n))
                        except Exception:
                            pass
        for k in res_keys:
            self.abort_reserve(k)
        self.reap()
        return dropped, len(res_keys)

    def stale_reservations(self, ttl: float) -> list:
        """Reservation keys older than ``ttl`` seconds (client reserved
        arena space but never sealed or aborted — dead mid-direct-put)."""
        now = time.monotonic()
        with self._lock:
            return [k for k, (_cid, ts) in self._reservations.items()
                    if now - ts > ttl]

    def sweep_orphan_slots(self) -> int:
        """True-up pass for ledger drift. Two rules, both safe because
        grants/reclaims serialize under the table lock: (a) a slot with
        outstanding refs but NO ledger holder carries only refs of
        already-reclaimed dead clients — force them to zero; (b) a slot
        whose SINGLE holder's charge exceeds observed refs had silent
        local releases — clamp the charge down (keeps ledger >= actual,
        the invariant reclaim's bound depends on). Returns refs dropped."""
        if self._shm is None:
            return 0
        dropped = 0
        with self._lock:
            holders: Dict[int, list] = {}
            for cid, grants in self._ext_slots.items():
                for slot in grants:
                    holders.setdefault(slot, []).append(cid)
            for slot in list(self._slot_owners):
                try:
                    observed = int(self._shm.ext_refs(slot))
                except Exception:
                    continue
                held_by = holders.get(slot, [])
                if observed > 0 and not held_by:
                    try:
                        dropped += int(self._shm.ext_release_n(slot,
                                                               observed))
                    except Exception:
                        pass
                elif len(held_by) == 1:
                    grants = self._ext_slots[held_by[0]]
                    if grants.get(slot, 0) > observed:
                        if observed == 0:
                            del grants[slot]
                            if not grants:
                                del self._ext_slots[held_by[0]]
                        else:
                            grants[slot] = observed
        return dropped

    def release(self, oid: bytes) -> None:
        if self._shm is not None:
            try:
                self._shm.release(oid)
            except Exception:
                pass

    # -- oid index (node-local object directory slice) -------------------
    def register_oid(self, ref: bytes, key: bytes, raw=None) -> None:
        if not ref:
            return
        with self._lock:
            self._by_oid[ref] = key
            self._ref_of[key] = ref
            if raw is not None:
                self._raw[key] = raw

    def key_for(self, ref: bytes) -> Optional[bytes]:
        with self._lock:
            return self._by_oid.get(ref)

    def raw_for(self, key: bytes):
        with self._lock:
            return self._raw.get(key)

    # -- arena spill tier (docs/object_plane.md "Arena spill") -----------
    # Cold, sealed, UNPINNED entries move to disk files under occupancy
    # pressure and restore on demand on every read path. A live external
    # slot ref (PR 16 grant ledger) pins an entry unspillable — a held
    # zero-copy view must never lose its backing bytes. Disarmed
    # (spill_dir None) every hook below is a None-check no-op.

    def _touch(self, key: bytes) -> None:
        """LRU maintenance on read grants (spill picks oldest first)."""
        if self.spill_dir is None:
            return
        with self._lock:
            if key in self._entries:
                self._entries.move_to_end(key)

    def _spill_path(self, key: bytes) -> str:
        return os.path.join(self.spill_dir, key.hex() + ".spill")

    def _pinned_now(self) -> set:
        """Keys unspillable RIGHT NOW: an outstanding external slot ref
        means some process still maps the bytes as a zero-copy view.
        Caller holds self._lock (grants commit under the same lock, so
        the set cannot go stale mid-pass)."""
        pinned = set()
        for slot, oid in self._slot_owners.items():  # raylint: disable=guarded-by — caller holds self._lock
            try:
                if int(self._shm.ext_refs(slot)) > 0:
                    pinned.add(oid)
            except Exception:
                pinned.add(oid)     # unreadable slot: keep it safe
        return pinned

    def _spill_one_locked(self, key: bytes, size: int) -> bool:
        """Spill ONE sealed entry. Caller holds self._lock and has
        checked the pin set. The write goes to a temp file renamed into
        place, and arena bytes free through the native deferred-delete/
        reap path — a reader that raced past the restore check keeps a
        valid (deferred) mapping and re-reads from disk next time."""
        if key in self._spilled or key not in self._entries:  # raylint: disable=guarded-by — caller holds self._lock
            return True     # idempotent: already parked / already gone
        if _fp.ENABLED:
            # drop/error arm = this spill attempt fails; the entry
            # stays resident at tier host-shm and a later pass retries
            try:
                if _fp.fire("arena.spill", key=key.hex()[:16],
                            nbytes=size) is _fp.DROP:
                    return False
            except Exception:
                return False
        try:
            view = self._shm.get_view(key)      # increfs
            try:
                data = view.tobytes()
            finally:
                self._shm.release(key)
        except Exception:
            return False
        path = self._spill_path(key)
        try:
            os.makedirs(self.spill_dir, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "wb") as fh:
                fh.write(data)
            os.replace(tmp, path)   # readers never see a torn file
        except OSError:
            return False
        self._spilled[key] = (path, len(data))  # raylint: disable=guarded-by — caller holds self._lock
        self._spilled_total += len(data)  # raylint: disable=guarded-by — caller holds self._lock
        self._entries.pop(key, None)  # raylint: disable=guarded-by — caller holds self._lock
        try:
            self._shm.delete(key)   # frees now, or defers until refs drop
        except Exception:
            pass
        self._spill_stats["spills"] += 1
        self._spill_stats["spilled_bytes"] += len(data)
        from ray_tpu.objectplane.tiers import count_spilled_bytes
        count_spilled_bytes(len(data))
        return True

    def _spill_pass_locked(self, need_bytes: Optional[int] = None,
                           floor_bytes: Optional[int] = None,
                           max_entries: int = 64,
                           exclude: tuple = ()) -> int:
        """Shared spill loop (caller holds self._lock): LRU-first until
        ``need_bytes`` of room exists / occupancy reaches
        ``floor_bytes`` / the per-pass entry bound or the spill-dir
        budget stops it. Returns entries spilled."""
        spilled = 0
        pinned = self._pinned_now()
        for key in list(self._entries):  # raylint: disable=guarded-by — caller holds self._lock
            if spilled >= max_entries:
                break
            used = self._shm.used_bytes()
            if need_bytes is not None and \
                    self.capacity - used >= need_bytes:
                break
            if floor_bytes is not None and used <= floor_bytes:
                break
            size = self._entries[key]  # raylint: disable=guarded-by — caller holds self._lock
            if key in exclude:
                continue
            if key in pinned:
                self._spill_stats["spill_skipped_pinned"] += 1
                continue
            if self.spill_budget and (self._spilled_total + size  # raylint: disable=guarded-by — caller holds self._lock
                                      > self.spill_budget):
                break       # disk budget exhausted: pressure goes hard
            if self._spill_one_locked(key, size):
                spilled += 1
        if spilled:
            try:
                self._shm.reap()
            except Exception:
                pass
        return spilled

    def spill_for(self, nbytes: int) -> bool:
        """Make ``nbytes`` of arena room by spilling cold entries; the
        put/reserve paths call this instead of failing over to the
        blob/dict path while cold data hogs the arena. False = spilling
        disarmed or not enough unpinned cold bytes."""
        if self.spill_dir is None or self._shm is None:
            return False
        with self._lock:
            self._spill_pass_locked(need_bytes=nbytes)
            return self.capacity - self._shm.used_bytes() >= nbytes

    def spill_to_fraction(self, target: float) -> int:
        """Proactive pressure-tick pass: bring occupancy down to the
        ``target`` fraction of capacity (soft watermark), oldest first,
        bounded per call so a tick stays short."""
        if self.spill_dir is None or self._shm is None:
            return 0
        with self._lock:
            return self._spill_pass_locked(
                floor_bytes=int(self.capacity * max(0.0, target)))

    def _maybe_restore(self, key: bytes) -> bool:
        """True when ``key`` is resident (nothing to do) or was
        restored; False when it is spilled and the restore failed —
        the caller degrades to a direct disk read."""
        if self.spill_dir is None:
            return True
        with self._lock:
            if key not in self._spilled:
                return True
        return self.restore(key)

    def restore(self, key: bytes) -> bool:
        """Bring a spilled entry back into the arena (tier spilled ->
        host-shm). Idempotent: a retried/concurrent restore finds the
        entry resident and reports success. The spill file is consumed
        only AFTER the arena copy lands — a failed attempt (failpoint
        arm, arena full) leaves the file intact for the next try."""
        if self._shm is None or self.spill_dir is None:
            return False
        done_bytes = 0
        with self._lock:
            spilled = self._spilled.get(key)
            if spilled is None:
                return True     # already resident (idempotent)
            path, size = spilled
            if _fp.ENABLED:
                # drop/error arm = this restore attempt fails; the read
                # path serves the spill file directly and retries later
                try:
                    if _fp.fire("arena.restore", key=key.hex()[:16],
                                nbytes=size) is _fp.DROP:
                        self._spill_stats["restore_failed"] += 1
                        return False
                except Exception:
                    self._spill_stats["restore_failed"] += 1
                    return False
            try:
                with open(path, "rb") as fh:
                    data = fh.read()
            except OSError:
                self._spill_stats["restore_failed"] += 1
                return False
            try:
                self._shm.put(key, data, pin=True)
            except KeyError:
                pass    # resident already (deferred twin / lost race)
            except Exception:
                # arena full: make room off colder entries, retry once
                # BEFORE consuming the spill file (the PR 5 object-store
                # lesson: pressure scan precedes the file delete)
                self._spill_pass_locked(need_bytes=len(data),
                                        exclude=(key,))
                try:
                    self._shm.put(key, data, pin=True)
                except KeyError:
                    pass
                except Exception:
                    self._spill_stats["restore_failed"] += 1
                    return False
            self._spilled.pop(key, None)
            self._spilled_total -= size
            self._entries[key] = len(data)
            self._entries.move_to_end(key)
            self._spill_stats["restores"] += 1
            self._spill_stats["restored_bytes"] += len(data)
            done_bytes = len(data)
        try:
            os.unlink(path)
        except OSError:
            pass
        from ray_tpu.objectplane.tiers import count_restored_bytes
        count_restored_bytes(done_bytes)
        return True

    def _read_spilled(self, key: bytes) -> Optional[bytes]:
        """Serve a spilled entry's bytes straight off its file (restore
        failed or lost a race with a spill pass) — reads degrade to
        disk, never to a miss."""
        with self._lock:
            spilled = self._spilled.get(key)
        if spilled is None:
            return None
        try:
            with open(spilled[0], "rb") as fh:
                return fh.read()
        except OSError:
            return None

    def spilled_bytes(self) -> int:
        with self._lock:
            return self._spilled_total

    def spill_stats(self) -> Dict[str, int]:
        with self._lock:
            out = dict(self._spill_stats)
            out["spilled_now_bytes"] = self._spilled_total
            out["spilled_now_count"] = len(self._spilled)
        return out

    # -- direct-put (reserve + client write + seal) ----------------------
    def reserve(self, key: bytes, size: int,
                client_id: str = UNKNOWN_CLIENT) -> Optional[int]:
        """Reserve arena space for a client-side write; None = no arena
        or no room (caller falls back to the blob path). Idempotent for
        a retried reserve of the same (key, size). The unsealed entry is
        charged to ``client_id`` so a writer that dies between reserve
        and seal gets its bytes reclaimed (reclaim_client or the TTL
        sweep) instead of stranding them forever."""
        if self._shm is None:
            return None
        from ray_tpu.native_store import ShmStoreFull
        try:
            off = self._shm.reserve(key, size)
        except ShmStoreFull:
            # spill cold entries to make room, then retry ONCE — a
            # direct put keeps succeeding in place instead of falling
            # back to the blob path while cold data hogs the arena
            if not self.spill_for(size):
                return None
            try:
                off = self._shm.reserve(key, size)
            except (ShmStoreFull, KeyError):
                return None
        except KeyError:
            return None
        with self._lock:
            self._reservations[key] = (client_id, time.monotonic())
        return off

    def seal(self, key: bytes, ref: bytes = b"", raw=None) -> bool:
        """Seal a reserved entry (idempotent; pin matches put(pin=True)
        so this layer's refcounting owns lifetime)."""
        if self._shm is None:
            return False
        try:
            self._shm.seal(key, pin=True)
        except KeyError:
            return False
        try:
            _off, size, _sealed = self._shm.stat(key)
        except Exception:
            size = 0
        with self._lock:
            self._reservations.pop(key, None)
            self._entries[key] = size
            self._entries.move_to_end(key)
        self.register_oid(ref, key, raw=raw)
        return True

    def abort_reserve(self, key: bytes) -> None:
        """Drop a reserved-but-never-sealed entry (failed direct put)."""
        self.delete(key)

    def reap(self) -> int:
        """Free deferred-deleted entries whose external (attached-
        process) refs have dropped; external releases are silent atomic
        decrements, so the owner sweeps periodically."""
        if self._shm is None:
            return 0
        try:
            return self._shm.reap()
        except Exception:
            return 0

    def contains(self, oid: bytes) -> bool:
        with self._lock:
            if oid in self._small or oid in self._spilled:
                return True
        return self._shm is not None and self._shm.contains(oid)

    def nbytes_of(self, oid: bytes) -> Optional[int]:
        with self._lock:
            blob = self._small.get(oid)
            if blob is None:
                spilled = self._spilled.get(oid)
                if spilled is not None:
                    return spilled[1]   # size answered without restore
        if blob is not None:
            return len(blob)
        if self._shm is not None:
            try:
                off, size = self._shm.get_ref(oid)
                self._shm.release(oid)
                return size
            except KeyError:
                return None
        return None

    def read_range(self, oid: bytes, off: int, size: int
                   ) -> Optional[bytes]:
        """One chunk of the object's bytes (inter-node chunked transfer,
        reference ``object_buffer_pool.h``); pin held only per call."""
        with self._lock:
            blob = self._small.get(oid)
        if blob is not None:
            return blob[off:off + size]
        if self._shm is not None:
            if not self._maybe_restore(oid):
                # arena still full: chunk straight off the spill file
                # so outbound push/pull never depends on arena room
                blob = self._read_spilled(oid)
                return None if blob is None else blob[off:off + size]
            try:
                view = self._shm.get_view(oid)  # increfs
                try:
                    self._touch(oid)
                    return bytes(view[off:off + size])
                finally:
                    self._shm.release(oid)
            except KeyError:
                return None
        return None

    def delete(self, oid: bytes) -> None:
        spill_path = None
        with self._lock:
            self._small.pop(oid, None)
            self._raw.pop(oid, None)
            self._reservations.pop(oid, None)
            self._entries.pop(oid, None)
            spilled = self._spilled.pop(oid, None)
            if spilled is not None:
                spill_path = spilled[0]
                self._spilled_total -= spilled[1]
            ref = self._ref_of.pop(oid, None)
            if ref is not None:
                self._by_oid.pop(ref, None)
        if spill_path is not None:
            try:
                os.unlink(spill_path)
            except OSError:
                pass
        if self._shm is not None:
            try:
                # an aborted direct put leaves an UNSEALED entry whose
                # creator ref was never pinned/released — drop it first
                # or the delete defers forever
                try:
                    _off, _size, sealed = self._shm.stat(oid)
                    if not sealed:
                        self._shm.release(oid)
                except KeyError:
                    pass
                self._shm.delete(oid)
            except Exception:
                pass

    def used_bytes(self) -> int:
        with self._lock:
            small = sum(len(b) for b in self._small.values())
        return small + (self._shm.used_bytes() if self._shm else 0)

    def close(self) -> None:
        if self._shm is not None:
            self._shm.close(unlink=True)


# ---------------------------------------------------------------------------
# pull manager: chunked, deduplicated, prioritized inter-node pulls
# ---------------------------------------------------------------------------

# Priorities mirror the reference's pull policy (``pull_manager.h:38-51``):
# an explicit ray.get outranks wait(fetch_local) outranks task-arg staging.
PULL_PRIORITY_GET = 0
PULL_PRIORITY_WAIT = 1
PULL_PRIORITY_TASK_ARGS = 2

def _pull_chunk() -> int:
    from ray_tpu._private.config import cfg
    return cfg().pull_chunk


class _Pull:
    __slots__ = ("oid", "from_addr", "priority", "event", "ok", "error",
                 "missing")

    def __init__(self, oid: bytes, from_addr, priority: int):
        self.oid = oid
        self.from_addr = from_addr
        self.priority = priority
        self.event = threading.Event()
        self.ok = False
        self.missing = False
        self.error = ""


class PullManager:
    """Inter-node object transfer engine (reference:
    ``object_manager.cc:247 Pull / :354 Push``, ``pull_manager.h``,
    ``push_manager.h``, ``object_buffer_pool.h``):

    - transfers move in ``PULL_CHUNK``-sized pieces assembled into one
      preallocated buffer, so a 64 MiB object never rides one RPC frame;
    - concurrent pulls of the same object deduplicate onto one in-flight
      transfer (push-dedup role — the bytes cross the wire once);
    - queued pulls are served strictly by priority (get > wait >
      task-args), then FIFO;
    - every step feeds stats counters (surfaced by ``daemon_stats``).
    """

    def __init__(self, objects: ObjectTable, peer_fn, num_workers: int = 2,
                 chunk: Optional[int] = None):
        self.objects = objects
        self._peer = peer_fn        # addr -> rpc.Client
        self.chunk = chunk if chunk is not None else _pull_chunk()
        self._cv = threading.Condition()
        self._heap: list = []
        self._seq = 0
        self._inflight: Dict[bytes, _Pull] = {}
        self.stats = {"pulls_started": 0, "pulls_deduped": 0,
                      "pulls_failed": 0, "chunks_transferred": 0,
                      "bytes_pulled": 0}
        for i in range(num_workers):
            threading.Thread(target=self._loop, daemon=True,
                             name=f"pull-worker-{i}").start()

    def request(self, oid: bytes, from_addr, priority: int) -> _Pull:
        """Enqueue (or join) a pull; caller waits on the returned event."""
        import heapq
        with self._cv:
            existing = self._inflight.get(oid)
            if existing is not None:
                self.stats["pulls_deduped"] += 1
                return existing
            pull = _Pull(oid, from_addr, priority)
            self._inflight[oid] = pull
            self.stats["pulls_started"] += 1
            self._seq += 1
            heapq.heappush(self._heap, (priority, self._seq, pull))
            self._cv.notify()
        return pull

    def _loop(self) -> None:
        import heapq
        while True:
            with self._cv:
                while not self._heap:
                    self._cv.wait()
                _, _, pull = heapq.heappop(self._heap)
            try:
                self._transfer(pull)
                pull.ok = True
            except _PullMissing:
                pull.missing = True
                with self._cv:
                    self.stats["pulls_failed"] += 1
            except Exception as e:  # noqa: BLE001 — reported to waiter
                pull.error = repr(e)
                with self._cv:
                    self.stats["pulls_failed"] += 1
            finally:
                with self._cv:
                    self._inflight.pop(pull.oid, None)
                pull.event.set()

    def _transfer(self, pull: _Pull) -> None:
        if _fp.ENABLED:
            # error arm fails this transfer attempt (waiter sees the
            # error and may fall back to the owner directory); delay
            # arm stretches the transfer window
            _fp.fire("daemon.pull_transfer")
        if self.objects.contains(pull.oid):
            return  # a deduped predecessor already landed it
        peer = self._peer(tuple(pull.from_addr))
        meta = peer.call("object_meta", oid=pull.oid)
        if meta.get("missing"):
            raise _PullMissing()
        size = meta["size"]
        if size <= self.chunk:
            out = peer.call("get_object", oid=pull.oid, prefer_shm=False)
            if out.get("missing"):
                raise _PullMissing()
            blob = out["blob"]
            with self._cv:
                self.stats["chunks_transferred"] += 1
                self.stats["bytes_pulled"] += len(blob)
        else:
            buf = bytearray(size)  # the transfer's reassembly buffer
            for off in range(0, size, self.chunk):
                want = min(self.chunk, size - off)
                out = peer.call("get_object_chunk", oid=pull.oid,
                                off=off, size=want)
                part = out.get("blob")
                if part is None:    # evicted mid-transfer
                    raise _PullMissing()
                buf[off:off + len(part)] = part
                with self._cv:
                    self.stats["chunks_transferred"] += 1
                    self.stats["bytes_pulled"] += len(part)
            blob = bytes(buf)
        self.objects.put(pull.oid, blob)


class _PullMissing(Exception):
    pass


# ---------------------------------------------------------------------------
# batched submit plumbing (driver side: cluster._SubmitCoalescer)
# ---------------------------------------------------------------------------

class _BatchTaskConn:
    """Adapts one batched task's reply surface onto the shared
    ``_run_pushed_task`` machinery: final outcomes ride the coalescing
    reply pump instead of a per-rid reply frame; stream TERMINATIONS
    (task_stream_end / task_stream_crash) coalesce onto the same pump
    as tagged entries, while task_yield items pass straight through to
    the real connection (they pace the gen_ack flow). ``key`` is the
    (task, attempt) dedupe identity — attempt included because task
    retries reuse the task id and must re-execute, not replay the old
    outcome. ``trace`` is (name, trace_id) for sampled tasks — it rides
    outcomes as ``tr`` so both sides can record the drain-side span
    phases (result_flush / result_ingest)."""

    __slots__ = ("service", "conn", "task_hex", "key", "trace",
                 "term_pump")

    def __init__(self, service: "DaemonService", conn: Connection,
                 task_hex: str, key: tuple, trace=None,
                 term_pump: bool = False):
        self.service = service
        self.conn = conn
        self.task_hex = task_hex
        self.key = key
        self.trace = trace
        self.term_pump = term_pump

    @property
    def closed(self) -> bool:
        return self.conn.closed

    def reply(self, rid, **kw) -> None:
        out = dict(kw)
        out["task"] = self.task_hex
        # fencing stamps: the attempt this outcome belongs to and the
        # daemon's registration epoch — the driver accepts exactly the
        # live (attempt, epoch) pair and counts the rest as fenced
        out["att"] = self.key[1]
        out["ep"] = self.service.epoch
        if self.trace is not None:
            out["tr"] = list(self.trace)
        self.service._batch_task_done(self.conn, self.key, out)

    def reply_error(self, rid, err: str) -> None:
        self.reply(rid, e=err)

    def push(self, method: str, **kw) -> None:
        if (self.term_pump
                and method in ("task_stream_end", "task_stream_crash")):
            # terminations are final per task: ship them coalesced —
            # but ONLY when the submitting driver advertised it can
            # ingest terminations off the pump (entry flag term_pump);
            # an older driver on a persistent daemon gets the classic
            # per-task push and never hangs its stream consumer
            out = dict(kw)
            out["stream"] = method
            out["att"] = self.key[1]
            out["ep"] = self.service.epoch
            self.service._batch_pump.add(self.conn, out)
            return
        self.conn.push(method, **kw)


class _BatchReplyPump:
    """Coalesces completed-task outcomes into ``task_batch_done`` push
    frames — one frame carries every completion that landed within the
    linger window (the batched-reply leg of the result pipeline).
    Final outcomes, object-location updates (``stored`` results), and
    generator/stream terminations all ride the same frames; classic
    ``via_pump`` submissions and ``push_task_batch`` tasks share it.

    Knobs: ``result_batch_max`` (entries per frame) and
    ``result_linger_us`` (straggler window).

    Retry contract: a flush that fails in transit
    (``batch.result_flush`` drop/error arms — the deterministic
    stand-in for a lost frame) requeues its entries and resends them
    on the next pump pass. Resends are idempotent at the driver: final
    outcomes pop their waiter slot exactly once (a duplicate finds no
    slot), and stream terminations land on an already-drained stream
    queue at worst."""

    def __init__(self, task_events=None, node_hex: str = ""):
        from ray_tpu._private.config import cfg
        self.linger_s = max(0.0, float(cfg().result_linger_us) / 1e6)
        self.max_per_frame = max(1, int(cfg().result_batch_max))
        # result_flush span sink (daemon lane); None in bare-pump tests
        self.task_events = task_events
        self.node_hex = node_hex
        self._cv = threading.Condition()
        # conn -> [(outcome, t_add)]: t_add is perf_counter at buffering
        # for traced outcomes (0.0 untraced — no clock read)
        self._buf: Dict[Connection, list] = {}  #: guarded by self._cv
        # async core: the pump is a call_later chain on the event loop —
        # one cross-thread wake per linger WINDOW (the arming hop), not
        # one per completion, and the flush runs where the write batcher
        # lives, so a chunk's push coalesces with other loop writes.
        # Threaded core: the dedicated cv-wait thread, as before.
        self._aloop = eventloop.get_loop() if cfg().async_core else None
        self._armed = False     #: guarded by self._cv (loop mode)
        if self._aloop is None:
            threading.Thread(target=self._loop, daemon=True,
                             name="batch-reply-pump").start()

    def add(self, conn: Connection, out: Dict[str, Any]) -> None:
        t_add = time.perf_counter() if "tr" in out else 0.0
        with self._cv:
            self._buf.setdefault(conn, []).append((out, t_add))
            if self._aloop is None:
                self._cv.notify()
                return
            if self._armed:
                return      # a flush is already scheduled: coalesce
            self._armed = True
        if eventloop.on_loop():
            self._arm_flush()  # raylint: disable=loop-affinity — on_loop() guard
        else:
            self._aloop.call_soon_threadsafe(self._arm_flush)

    def _arm_flush(self, backoff: float = 0.0) -> None:  #: loop-only
        delay = max(self.linger_s, backoff)
        if delay > 0:
            self._aloop.call_later(delay, self._flush_on_loop)
        else:
            self._aloop.call_soon(self._flush_on_loop)

    def _flush_on_loop(self) -> None:  #: loop-only
        with self._cv:
            buf, self._buf = self._buf, {}
            self._armed = False
        failed = False
        for conn, entries in buf.items():
            if conn.closed:
                continue
            i = 0
            while i < len(entries):
                chunk = entries[i:i + self.max_per_frame]
                if not self._send_chunk(conn, chunk):
                    # lost in transit: requeue, preserving order (the
                    # resend is idempotent at the driver); concurrent
                    # add()s may have re-armed already — checked below
                    failed = True
                    with self._cv:
                        self._buf.setdefault(conn, [])[:0] = entries[i:]
                    break
                i += self.max_per_frame
        if failed:
            with self._cv:
                re_arm = not self._armed and bool(self._buf)
                if re_arm:
                    self._armed = True
            if re_arm:
                # the 1ms floor is the same retry backoff the threaded
                # pump applies after a failed pass (no busy-spin at
                # linger 0 against a failing-but-open connection)
                self._arm_flush(backoff=0.001)

    def _loop(self) -> None:
        failed_last_pass = False
        while True:
            with self._cv:
                while not self._buf:
                    self._cv.wait()
            # short linger: completions that land together leave
            # together. After a failed pass the linger acts as retry
            # backoff too — floored so a linger of 0 cannot busy-spin
            # the pump against a persistently failing (but not yet
            # closed) connection.
            linger = self.linger_s
            if failed_last_pass:
                linger = max(linger, 0.001)
            if linger:
                time.sleep(linger)
            with self._cv:
                buf, self._buf = self._buf, {}
            failed_last_pass = False
            for conn, entries in buf.items():
                if conn.closed:
                    continue
                i = 0
                while i < len(entries):
                    chunk = entries[i:i + self.max_per_frame]
                    if not self._send_chunk(conn, chunk):
                        # lost in transit: requeue this chunk AND the
                        # rest, preserving order — the resend is
                        # idempotent at the driver. A dead connection
                        # drops out at the next pass's closed check.
                        failed_last_pass = True
                        with self._cv:
                            self._buf.setdefault(conn, [])[:0] = \
                                entries[i:]
                        break
                    i += self.max_per_frame

    def _send_chunk(self, conn: Connection, chunk) -> bool:
        if _fp.ENABLED:
            try:
                # drop/error arm = the frame is lost in transit; the
                # caller requeues and the next pass resends
                if _fp.fire("batch.result_flush",
                            n=len(chunk)) is _fp.DROP:
                    return False
            except Exception:
                return False
        now = time.perf_counter()
        conn.push("task_batch_done", outcomes=[o for o, _ in chunk])
        if conn.closed:     # push swallows transport failure into closed
            return False
        dwell = max((now - t for _, t in chunk if t), default=0.0)
        if dwell:
            _metrics.note_queue_dwell("daemon.reply_pump", dwell)
        if self.task_events is not None:
            self._record_flush_spans(chunk, now)
        return True

    def _record_flush_spans(self, chunk, now: float) -> None:
        """result_flush phase: completion buffered on the pump -> its
        frame on the wire (daemon lane, traced outcomes only)."""
        try:
            for out, t_add in chunk:
                tr = out.get("tr")
                if not tr or not t_add:
                    continue
                _events.record_phase(
                    self.task_events, task_id=out.get("task", ""),
                    name=tr[0], phase="result_flush",
                    dur_s=max(now - t_add, 0.0), node_id=self.node_hex,
                    proc=f"daemon:{self.node_hex[:8]}", trace_id=tr[1],
                    start_wall=_events.wall_at(t_add), end_mono=now)
        except Exception:
            pass    # observability must never fail a flush


# completed batched-task outcomes kept for duplicate-frame resend; cap
# bounds the inline result blobs a slow driver can pin here
_BATCH_DONE_CAP = 512


# ---------------------------------------------------------------------------
# the daemon's runtime shim (what WorkerClient/_core paths need)
# ---------------------------------------------------------------------------

class _NodeStub:
    __slots__ = ("node_id",)

    def __init__(self, node_id: NodeID):
        self.node_id = node_id


class DaemonRuntime:
    """Forwards worker-initiated core ops to the owner (driver)."""

    def __init__(self, service: "DaemonService"):
        self.service = service
        self.job_id = None
        self.namespace = None
        self._shutdown = False
        from ray_tpu._private.worker_process import ProcessRouter

        self.process_router = ProcessRouter(self)

    @property
    def task_events(self):
        """Span sink for this daemon's workers (trace_push lands here;
        the heartbeat loop flushes it to the head)."""
        return self.service.task_events

    def shm_ops(self, call: str, kw: Dict[str, Any], client=None):
        """Daemon-LOCAL object-plane ops for this daemon's workers
        (never forwarded to the owner): meta resolution for zero-copy
        gets, reserve/seal/abort for direct puts. The worker side only
        issues these once its arena attach succeeded. ``client`` is the
        issuing WorkerClient — grants get charged to its (pid,
        generation) identity for crash reclamation."""
        return self.service.handle_worker_shm_op(call, kw, client)

    def forward_core_op(self, msg: Dict[str, Any]) -> Tuple[bool, bytes]:
        owner = self.service.owner
        if owner is None:
            raise RuntimeError("daemon has no owner connection")
        # prefer the globally-unique borrower key; the bare worker rid
        # collides across workers/daemons at the shared owner holder
        out = owner.call("core_op", call=msg["call"],
                         payload=msg["payload"],
                         task=msg.get("task_key") or msg.get("task"),
                         timeout=None)
        return out["ok"], out["value"]

    def on_actor_worker_died(self, actor_id: ActorID, cause: str) -> None:
        self.service.notify_driver("actor_worker_died",
                                   actor_id=actor_id.hex(), cause=cause)


# ---------------------------------------------------------------------------
# service
# ---------------------------------------------------------------------------

class DaemonService:
    def __init__(self, node_id_hex: str, resources: Dict[str, float],
                 object_store_bytes: int, persist: bool = False,
                 host: str = "127.0.0.1"):
        self.node_id = NodeID.from_hex(node_id_hex)
        self.resources = resources
        # persist=True (cluster started via `ray-tpu start`): survive
        # driver disconnects and serve the next driver; False (driver-
        # spawned session): die with the driver.
        self.persist = persist
        from ray_tpu._private.config import cfg as _cfg
        # Spill armed only under the memory_pressure master switch: a
        # disarmed table keeps every hook a None-check no-op
        # (zero-overhead-when-off, the netchaos discipline).
        spill_dir = None
        if _cfg().memory_pressure:
            spill_dir = (_cfg().arena_spill_dir
                         or os.path.join("/tmp", f"rtpu_spill_{node_id_hex[:12]}"))
        self.objects = ObjectTable(
            f"rtpu_{node_id_hex[:12]}", object_store_bytes,
            spill_dir=spill_dir,
            spill_budget=int(_cfg().arena_spill_budget_bytes))
        # Hand the arena to every worker this daemon spawns (the
        # worker-hello leg of the zero-copy plane): workers attach the
        # segment by name and resolve host-tier objects in place.
        if self.objects._shm is not None and _cfg().objectplane_attach:
            from ray_tpu._private import worker_process as _wp
            _wp.set_arena_info(self.objects.arena_name,
                               self.objects._shm.capacity())
        self.owner: Optional[Client] = None
        self.driver_conn: Optional[Connection] = None
        # fencing epoch minted by the head at register_node (0 =
        # standalone / never registered); stamped into heartbeats,
        # hello replies, and every result/stream frame so drivers can
        # fence a healed pre-death incarnation's late results
        self.epoch = 0
        # per-process span buffer (task_event_buffer.cc role): daemon
        # dispatch spans + this daemon's worker exec spans, flushed to
        # the head's task-event store on heartbeats (main loop)
        from ray_tpu._private.events import TaskEventBuffer
        self.task_events = TaskEventBuffer(capacity=50_000)
        self.runtime = DaemonRuntime(self)
        self.node_stub = _NodeStub(self.node_id)
        self._lock = tracked_lock("daemon.ledger", reentrant=False)
        #: guarded by self._lock
        self._leases: Dict[str, Any] = {}          # lease_id -> WorkerClient
        self._lease_seq = 0                        #: guarded by self._lock
        # task_id hex -> (client, worker rid) for cancel/gen_ack
        self._task_rids: Dict[str, Tuple[Any, str]] = {}  #: guarded by self._lock
        # task_id hex -> job hex: OOM-preemption attribution (the
        # tenant-aware policy prefers over-quota jobs' workers); pruned
        # against _task_rids in _memory_candidates
        self._task_jobs: Dict[str, str] = {}       #: guarded by self._lock
        # node memory-pressure level, advertised through heartbeats/
        # syncer gossip and pushed to the driver on transitions; stays
        # "ok" forever when cfg().memory_pressure is off
        self.pressure: Optional[Any] = None        # PressureController
        # batched-submit dedupe, keyed (task hex, attempt): a retried
        # push_task_batch frame must not double-execute — running tasks
        # are skipped, finished ones get their recorded outcome resent;
        # a task RETRY bumps the attempt and executes normally
        self._batch_running: set = set()           #: guarded by self._lock
        #: guarded by self._lock
        self._batch_done: "OrderedDict[tuple, Dict[str, Any]]" = OrderedDict()
        self._batch_pump = _BatchReplyPump(
            task_events=self.task_events, node_hex=self.node_id.hex())
        self._bundles: Dict[Tuple[str, int], Dict[str, Any]] = {}  #: guarded by self._lock
        self._peers: Dict[Tuple[str, int], Client] = {}  #: guarded by self._lock
        # cross-language actors: name -> [actor_id, seqno]
        self._xlang_actors: Dict[str, list] = {}   #: guarded by self._lock
        self.head_addr = None            # set by main() in daemon mode
        self._xlang_head_client = None
        # peer resource gossip (reference: ray_syncer.h:83): versioned
        # per-node load entries, merged peer-to-peer; loop starts in
        # main() once the head address is known
        self._syncer_view: Dict[str, Dict[str, Any]] = {}  #: guarded by self._syncer_lock
        self._syncer_lock = tracked_lock("daemon.syncer", reentrant=False)
        self._syncer_peers_cache: Dict[str, Any] = {}
        self._syncer_peers_ts = 0.0
        self._syncer_interval_s = float(
            os.environ.get("RAY_TPU_SYNCER_INTERVAL_S", "0.5"))
        # Task bodies block on worker IPC, so the pool is sized well past
        # core count; reusing threads beats per-task spawn under GIL
        # contention (reference: raylet dispatches from its event loop).
        # The cap must exceed the driver's per-node in-flight bound (256,
        # node.py max_worker_threads): a parent task blocked in get() on
        # a child routed here holds a pool thread, and a cap at or below
        # the in-flight bound could starve the child of a thread.
        from ray_tpu._private.thread_pool import DaemonThreadPool
        self._task_pool = DaemonThreadPool(1024, name="daemon-task")
        self.pulls = PullManager(self.objects, self._peer)
        # proactive node-to-node transfer (the push direction; dedupes
        # in flight, against the owner's directory, and against pulls)
        from ray_tpu.objectplane.push import PushManager, PushReceiver
        self.pushes = PushManager(self.objects, self._peer,
                                  locate_fn=self._locate_via_owner)
        self.push_rx = PushReceiver(self.objects,
                                    register_oid=self.objects.register_oid)
        # Native daemon core (native/daemon_core.cc): the C++ event loop
        # that owns the plain-task hot path — drivers submit straight to
        # it, it leases a dedicated worker, forwards the payload, routes
        # the outcome back; zero Python per task (reference: the raylet's
        # C++ lease/dispatch loop, node_manager.cc). This Python service
        # remains the policy shell (actors, PGs, runtime envs, objects).
        self.fast_core = None
        self.fast_port: Optional[int] = None
        self._fast_host = host
        self._fast_workers: list = []
        self._fast_tag_seq = 0        # targeted-lane (actor) tags
        self._fast_max = max(1, min(16, int(resources.get("CPU", 2) or 2)))
        try:
            from ray_tpu._private.fast_lane import CoreHandle
            core = CoreHandle()
            # bind exactly where the daemon's RPC server binds: a
            # loopback daemon must not open a network-reachable
            # task-submission (= code execution) port
            port = core.start(host, 0)
            if port:
                self.fast_core = core
                self.fast_port = port
                threading.Thread(target=self._fast_pool_loop,
                                 daemon=True,
                                 name="fastlane-pool").start()
        except Exception:
            self.fast_core = None
        # Worker log capture: this daemon's workers write per-pid files;
        # the monitor forwards new lines to the driver (worker_log push).
        from ray_tpu._private import log_monitor as _lm
        self._log_monitor = None
        if _lm.log_to_driver_enabled():
            self._log_monitor = _lm.LogMonitor(
                _lm.session_log_dir(), self._forward_worker_log)
        # continuous profiler (profiling_hz knob, default off): this
        # daemon's record plus worker records ingested off result
        # frames ship to the head each heartbeat (main loop)
        _profiling.maybe_start_from_config(f"daemon:{node_id_hex[:8]}")

    # -- fast lane (native core) workers --------------------------------
    def _fast_dedicate_worker(self):
        """Spawn a worker dedicated to the native core's task lane. Its
        mp channel stays open for host ops (fetch_function, nested core
        ops, metrics); it never enters the classic idle pool."""
        from ray_tpu._private import worker_process as wp

        w = wp._spawn_worker()
        # NOT _checked_out: lane workers never enter the idle pool and
        # must not skew the pool's active-checkout accounting on death
        w.fast_lane = True
        w.raw_outcomes = True
        w.runtime = self.runtime
        w.node = self.node_stub
        lane_host = ("127.0.0.1" if self._fast_host in ("0.0.0.0", "")
                     else self._fast_host)
        rid, pend = w._request({
            "op": "join_fast_lane",
            "addr": [lane_host, self.fast_port]})
        out = w._wait_outcome(rid, pend)
        if out[0] not in ("ok", "ok_raw"):
            try:
                w.kill(expected=True)
            except Exception:
                pass
            raise RuntimeError(f"fast-lane join failed: {out!r}")
        # close the hello/spawn race: a set_extra_sys_path that landed
        # between this worker's boot snapshot and now re-sends here
        wp.ensure_sys_path(w)
        return w

    def _fast_pool_loop(self) -> None:
        """Queue-depth-driven sizing of the dedicated fast-lane workers:
        at least one alive; grow one at a time while the core reports a
        backlog, up to the node's CPU capacity (reference: worker-pool
        prestart + autoscaling-by-demand)."""
        while True:
            try:
                from ray_tpu._private import worker_process as wp
                alive = [w for w in self._fast_workers if w.alive()]
                self._fast_workers = alive
                for w in alive:
                    wp.ensure_sys_path(w)   # no-op when current
                stats = (self.fast_core.stats()
                         if self.fast_core is not None else {})
                grow = (not alive
                        or (stats.get("queued", 0) > 0
                            and len(alive) < self._fast_max))
                if grow:
                    self._fast_workers.append(
                        self._fast_dedicate_worker())
                    continue   # re-check immediately while backlogged
            except Exception:
                time.sleep(1.0)
            time.sleep(0.25)

    def _forward_worker_log(self, pid: int, stream: str,
                            line: str) -> None:
        self.notify_driver("worker_log", pid=pid, stream=stream,
                           line=line, node=self.node_id.hex()[:8])

    def _peer(self, addr: Tuple[str, int]) -> Client:
        # dial OUTSIDE the lock: holding it across a TCP connect
        # stalled every other peer lookup for the dial's duration.
        # Losing a dial race just closes the extra connection.
        with self._lock:
            peer = self._peers.get(addr)
        if peer is not None and not peer.dead:
            return peer
        fresh = rpc.connect(addr)
        with self._lock:
            peer = self._peers.get(addr)
            if peer is not None and not peer.dead:
                pass        # raced: keep the established winner
            else:
                peer = self._peers[addr] = fresh
        if peer is not fresh:
            fresh.close()
        return peer

    def _locate_via_owner(self, oid: bytes):
        """Owner-keyed object directory (reference:
        ``ownership_object_directory.h``): ask the object's owner which
        nodes hold a copy."""
        if self.owner is None:
            return []
        out = self.owner.call(
            "core_op", call="locate_object",
            payload=cloudpickle.dumps({"oid": oid}), task=None,
            timeout=30.0)
        if not out.get("ok"):
            return []
        return cloudpickle.loads(out["value"])

    # -- wiring ----------------------------------------------------------
    def handle_hello_driver(self, conn, rid, msg):
        self.driver_conn = conn
        conn.link("driver")
        self.owner = rpc.connect(tuple(msg["owner_addr"]),
                                 timeout=None).link("driver")
        self.runtime.job_id = cloudpickle.loads(msg["job_id"])
        self.runtime.namespace = msg["namespace"]
        # driver import roots: future workers get them in the boot
        # frame; already-running ones (prestarted pool, fast lane) get
        # an extend op so by-reference pickles resolve immediately
        from ray_tpu._private import worker_process as _wp
        paths = list(msg.get("sys_path") or [])
        if paths:
            _wp.set_extra_sys_path(paths)
            for w in _wp.live_workers():
                try:
                    w.notify_extend_sys_path(paths)
                except Exception:
                    pass
            for w in list(self._fast_workers):
                try:
                    w.notify_extend_sys_path(paths)
                except Exception:
                    pass
        # Don't report ready until the worker pool is warm: the first
        # lease otherwise pays a cold fork while racing driver work for
        # the CPU (reference: worker prestart hides process start cost).
        from ray_tpu._private import worker_process as wp

        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            with wp._POOL_LOCK:
                if wp._IDLE:
                    break
            time.sleep(0.02)
        return {"ok": True, "pid": os.getpid(),
                "fast_port": self.fast_port,
                # protocol feature flags: this daemon understands
                # push_task_batch (drivers fall back per-task
                # otherwise) and coalesced completion delivery for
                # classic submit_task calls (via_pump)
                "batch": True,
                "result_batch": True,
                # fair-share federation: this daemon accepts
                # tenancy_sync job tables (old drivers never send
                # them and keep unconditional admission)
                "tenancy": True,
                # partition fencing: result/stream frames carry epoch
                # (ep) and attempt (att) stamps; the registration
                # epoch rides along so the driver knows the live
                # incarnation (old daemons advertise neither and the
                # driver accepts frames unfenced)
                "fence": True,
                # which wire+dispatch core this daemon runs (frames are
                # identical either way — purely observational, see
                # capabilities.py)
                "async_core": self._batch_pump._aloop is not None,
                "epoch": self.epoch,
                # zero-copy object plane: same-host clients attach this
                # arena by name for direct puts / slot-ref'd gets
                "objectplane": self.objects._shm is not None,
                "arena": self.objects.arena_name,
                "arena_capacity": (self.objects._shm.capacity()
                                   if self.objects._shm else 0),
                # connection-scoped grant-ledger identity: every slot
                # grant / reservation this connection requests is
                # charged here and reclaimed when the connection dies
                "client_id": self._conn_client_id(conn)}

    def notify_driver(self, kind: str, **kw) -> None:
        conn = self.driver_conn
        if conn is not None and not conn.closed:
            conn.push(kind, **kw)

    def on_disconnect(self, conn: Connection) -> None:
        cid = None
        try:
            cid = conn.meta.get("arena_client_id")
        except Exception:
            pass
        if cid is not None:
            # connection gone (clean close and SIGKILL look the same
            # here): reclaim every grant/reservation charged to it
            self.reclaim_client(cid, "disconnect")
        if conn is self.driver_conn:
            if self.persist:
                # Shared cluster (`ray-tpu start`): drop the departed
                # driver's state and wait for the next one.
                self._reset_for_new_driver()
                return
            # Driver gone: this daemon's work is orphaned; exit like a
            # raylet whose GCS/driver session ended.
            threading.Thread(target=lambda: (time.sleep(0.2),
                                             os._exit(0)),
                             daemon=True).start()

    def _reset_for_new_driver(self) -> None:
        """Tear down the departed driver's leases/actors so the next
        driver starts clean (its objects stay until arena pressure —
        known cross-driver growth, bounded by the arena capacity)."""
        self.driver_conn = None
        old_owner, self.owner = self.owner, None
        if old_owner is not None:
            try:
                old_owner.close()
            except OSError:
                pass
        with self._lock:
            leases = list(self._leases.values())
            self._leases.clear()
            self._task_rids.clear()
            self._bundles.clear()
            self._batch_running.clear()
            self._batch_done.clear()
        for client in leases:   # leased mid-task: state unknown, kill
            try:
                client.kill(expected=True)
            except Exception:
                pass
        router = self.runtime.process_router
        with router._lock:
            actors = dict(router._actor_workers)
            router._actor_workers.clear()
        for client in actors.values():
            try:
                client.kill(expected=True)
            except Exception:
                pass

    # -- object-plane crash reclamation ----------------------------------
    def reclaim_client(self, client_id: str, reason: str
                       ) -> Tuple[int, int]:
        """One funnel for every death signal — worker pipe EOF, fast-
        lane generation death, RPC connection close — that drops the
        dead client's grants, aborts its reservations, and reaps, so
        deferred deletes free NOW instead of at daemon restart. Returns
        (refs dropped, reservations aborted); idempotent per client."""
        if _fp.ENABLED:
            try:
                # drop/error arm = the event-path reclaim is LOST (the
                # death signal raced a daemon hiccup); the heartbeat
                # orphan sweep is the backstop and must still converge
                # the leak gauge to zero
                if _fp.fire("arena.grant_reclaim", client=client_id,
                            reason=reason) is _fp.DROP:
                    return (0, 0)
            except Exception:
                return (0, 0)
        try:
            dropped, aborted = self.objects.reclaim_client(client_id)
        except Exception:
            return (0, 0)   # reclamation must never take the daemon down
        if dropped or aborted:
            from ray_tpu.objectplane import tiers as _tiers
            _tiers.count_grants_reclaimed(dropped, reason)
        return dropped, aborted

    def sweep_object_plane(self) -> None:
        """Heartbeat orphan sweep: the backstop for anything the event-
        path reclaim missed — reservations stale past the TTL (writer
        died between reserve and seal), grants charged to worker pids
        that no longer exist, and ledger drift from silent local
        releases (sweep_orphan_slots). Faults here must never take the
        beat down."""
        obj = self.objects
        if _fp.ENABLED:
            try:
                # drop/error arm = this sweep pass is skipped wholesale
                # (a later beat retries); delay stretches the pass
                if _fp.fire("arena.reservation_sweep") is _fp.DROP:
                    return
            except Exception:
                return
        try:
            ttl = float(os.environ.get("RAY_TPU_ARENA_RESERVE_TTL_S",
                                       "30"))
        except ValueError:
            ttl = 30.0
        stale = obj.stale_reservations(ttl)
        for key in stale:
            try:
                obj.abort_reserve(key)
            except Exception:
                pass
        if stale:
            from ray_tpu.objectplane import tiers as _tiers
            _tiers.count_stale_reservations(len(stale))
        # grants held by dead worker pids the pipe-EOF callback missed
        for cid in obj.ledger_clients():
            if not cid.startswith("w:"):
                continue    # conn-scoped ids reclaim via on_disconnect
            try:
                pid = int(cid.split(":")[1])
            except (IndexError, ValueError):
                continue
            if pid > 0 and not _pid_alive(pid):
                self.reclaim_client(cid, "sweep")
        dropped = obj.sweep_orphan_slots()
        if dropped:
            from ray_tpu.objectplane import tiers as _tiers
            _tiers.count_grants_reclaimed(dropped, "sweep")
        obj.reap()

    def slot_ref_attribution(self) -> Dict[str, Any]:
        """slot_ref_stats plus liveness: each ledger client row gains
        its parsed pid (worker identities only) and whether that pid is
        still alive, so operators can see WHO holds a slot and whether
        the holder is a reclamation candidate."""
        stats = self.objects.slot_ref_stats(attribution=True)
        for row in stats.get("clients", ()):
            pid = None
            cid = row.get("client", "")
            if cid.startswith("w:"):
                try:
                    pid = int(cid.split(":")[1])
                except (IndexError, ValueError):
                    pid = None
            row["pid"] = pid
            row["alive"] = _pid_alive(pid) if pid else None
        return stats

    # -- worker lease protocol ------------------------------------------
    def handle_request_worker_lease(self, conn, rid, msg):
        """Grant a pooled worker (reference: HandleRequestWorkerLease →
        WorkerPool::PopWorker)."""
        from ray_tpu._private import worker_process as wp

        if _fp.ENABLED:
            # delay arm = slow lease grant; error arm = lease denied
            # (surfaces as a RemoteError at the driver)
            _fp.fire("daemon.lease")
        client = wp.acquire_worker()
        client.raw_outcomes = True
        client.runtime = self.runtime
        client.node = self.node_stub
        with self._lock:
            self._lease_seq += 1
            lease_id = f"l{self._lease_seq}"
            self._leases[lease_id] = client
        return {"lease_id": lease_id, "worker_pid": client.proc.pid}

    def handle_return_worker(self, conn, rid, msg):
        from ray_tpu._private import worker_process as wp

        with self._lock:
            client = self._leases.pop(msg["lease_id"], None)
        if client is not None and client.actor_id is None:
            wp.release_worker(client)
        return {"ok": True}

    def _leased(self, lease_id: str):
        with self._lock:
            client = self._leases.get(lease_id)
        if client is None:
            raise KeyError(f"unknown lease {lease_id!r}")
        return client

    # -- task execution --------------------------------------------------
    def _pump_outcome(self, conn, rid, client, spec, outcome,
                      on_done=None) -> None:
        """Shared reply/stream pump for push_task and call_actor_method:
        inline or stored result, generator stream pushes, worker-crash
        reporting. ``on_done(crashed: bool)`` runs when the interaction —
        including any stream — is over."""
        from ray_tpu._private.worker_process import WorkerCrashed

        task_hex = spec.task_id.hex()
        if outcome[0] == "gen":
            conn.reply(rid, outcome="gen")
            crashed = False
            try:
                # ep: the daemon's fencing epoch rides every stream
                # push so the driver can reject a healed pre-death
                # incarnation's late stream frames
                for kind, blob in outcome[1]:
                    if kind == "yield_raw":
                        conn.push("task_yield", task=task_hex,
                                  blob=blob, ep=self.epoch)
                    else:
                        conn.push("task_stream_end", task=task_hex,
                                  ok=False, blob=blob, ep=self.epoch)
                        break
                else:
                    conn.push("task_stream_end", task=task_hex,
                              ok=True, blob=b"", ep=self.epoch)
            except WorkerCrashed as e:
                crashed = True
                client.kill(expected=False)
                conn.push("task_stream_crash", task=task_hex,
                          error=str(e), ep=self.epoch)
            finally:
                with self._lock:
                    self._task_rids.pop(task_hex, None)
                if on_done is not None:
                    on_done(crashed)
            return
        with self._lock:
            self._task_rids.pop(task_hex, None)
        try:
            ok = outcome[0] == "ok_raw"
            blob = outcome[1]
            if ok and len(blob) > INLINE_RESULT:
                oid = b"res:" + spec.task_id.binary()
                self.objects.put(oid, blob)
                n = spec.num_returns
                if spec.return_ids and (n == 1 or not isinstance(n, int)):
                    # node-local oid index: same-node attached workers
                    # resolve this result without the owner round trip.
                    # Multi-return (int n > 1) blobs hold the WHOLE
                    # tuple — the driver fetches once and splits
                    # (worker.py stored path); indexing ref0 here would
                    # hand consumers the tuple as ref0's value
                    self.objects.register_oid(
                        spec.return_ids[0].binary(), oid)
                conn.reply(rid, outcome="stored", oid=oid,
                           nbytes=len(blob))
            else:
                conn.reply(rid, outcome="ok" if ok else "err", blob=blob)
        finally:
            if on_done is not None:
                on_done(False)

    def handle_submit_task(self, conn, rid, msg):
        """Fused lease+push+release in ONE round trip — the common task
        path. The explicit lease protocol (request_worker_lease /
        push_task / return_worker) remains for callers that need to hold
        a worker across calls; the reference gets the same effect by
        caching leases per SchedulingKey
        (``transport/normal_task_submitter.cc:140``).

        ``via_pump`` submissions (driver saw ``result_batch`` in hello)
        get their COMPLETION on the coalesced task_batch_done pump
        instead of this RPC's reply: the reply is an immediate ack, so
        the classic path's completions batch exactly like the
        push_task_batch path's. Dedupe keys on (task, attempt) in the
        shared batch tables — a retried frame never double-executes."""
        if msg.get("via_pump") and msg.get("task"):
            return self._submit_task_via_pump(conn, msg)
        from ray_tpu._private import worker_process as wp

        msg["_t0"] = time.perf_counter()    # dispatch-phase span start
        client = wp.acquire_worker()
        client.raw_outcomes = True
        client.runtime = self.runtime
        client.node = self.node_stub
        try:
            return self._run_pushed_task(conn, rid, msg, client,
                                         lease_id=None)
        except BaseException:
            # e.g. an unpicklable spec: without this the checked-out
            # worker (and its _ACTIVE slot) would leak per failed submit.
            wp.release_worker(client)
            raise

    def _submit_task_via_pump(self, conn, msg):
        """Classic single-task submit whose outcome returns coalesced
        (the 'classic submitters ride the result pipeline too' leg)."""
        key = (msg["task"], msg.get("attempt", 0))
        msg["_t0"] = time.perf_counter()    # dispatch-phase span start
        resend = None
        with self._lock:
            if key in self._batch_running:
                return {"outcome": "pump"}  # duplicate of in-flight
            resend = self._batch_done.get(key)
            if resend is None:
                self._batch_running.add(key)
        if resend is not None:
            self._batch_pump.add(conn, resend)
            return {"outcome": "pump"}
        self._start_batch_task(conn, msg, key)
        return {"outcome": "pump"}

    @rpc.loop_safe
    def handle_push_task_batch(self, conn, rid, msg):
        """Coalesced submit: N tasks on one frame (driver-side
        _SubmitCoalescer). Each task runs exactly like submit_task —
        fused lease+push+release on a pooled worker — but the per-task
        RPC round trip is gone: the frame is acked once, and completions
        return batched on task_batch_done push frames.

        loop_safe: on the async core this runs inline on the event loop
        (dedupe is dict ops under a short lock hold; nothing blocks),
        so frame parse -> admission -> ack has zero thread hand-offs.
        The per-task pool submits — which may cold-SPAWN pool threads —
        are fanned out by ONE pool job below, keeping spawn cost off
        the loop.

        Idempotent by task id: a retried frame (driver saw its flush
        fail in transit) skips tasks already running and resends the
        recorded outcome of tasks already finished — never a second
        execution."""
        for fid, blob in (msg.get("fns") or {}).items():
            # content-addressed (fid == sha1(blob)): registering under
            # the same id the driver computed lets workers resolve
            # fetch_function locally with no driver round trip
            from ray_tpu._private import worker_process as wp
            wp.register_function_blob(blob)
        resend = []
        starts = []
        for entry in msg["tasks"]:
            # dedupe identity is (task, attempt): a RETRY reuses the
            # task id but must execute — only a resent frame of the
            # SAME attempt is a duplicate
            key = (entry["task"], entry.get("attempt", 0))
            entry["_t0"] = time.perf_counter()  # dispatch-phase span
            with self._lock:
                if key in self._batch_running:
                    continue        # duplicate of an in-flight task
                done = self._batch_done.get(key)
                if done is not None:
                    resend.append(done)
                    continue
                self._batch_running.add(key)
            starts.append(self._start_batch_task(conn, entry, key,
                                                 defer=True))
        if starts:
            if len(starts) == 1 or not eventloop.on_loop():
                for s in starts:
                    self._task_pool.submit(s)
            else:
                def _fan_out():
                    for s in starts:
                        self._task_pool.submit(s)
                self._task_pool.submit(_fan_out)
        for out in resend:
            self._batch_pump.add(conn, out)
        return {"ok": True, "accepted": len(msg["tasks"])}

    def _start_batch_task(self, conn, entry, key: tuple,
                          defer: bool = False):
        """Acquire a pooled worker OFF the RPC lane thread (the pool may
        cold-spawn a process) and run the shared pushed-task machinery
        with the batch reply adapter. ``defer=True`` returns the start
        closure instead of submitting it (batch fan-out)."""
        trace = ((entry.get("name", ""), entry["trace"])
                 if entry.get("trace") else None)
        bconn = _BatchTaskConn(self, conn, entry["task"], key,
                               trace=trace,
                               term_pump=bool(entry.get("term_pump")))

        def start():
            from ray_tpu._private import worker_process as wp

            try:
                client = wp.acquire_worker()
            except BaseException as e:  # noqa: BLE001 — shipped back
                bconn.reply_error(None, f"{type(e).__name__}: {e}")
                return
            client.raw_outcomes = True
            client.runtime = self.runtime
            client.node = self.node_stub
            try:
                self._run_pushed_task(bconn, None, entry, client,
                                      lease_id=None)
            except BaseException as e:  # noqa: BLE001 — e.g. an
                # undecodable spec: release the checkout and fail just
                # this task, not the whole batch
                wp.release_worker(client)
                bconn.reply_error(None, f"{type(e).__name__}: {e}")

        if defer:
            return start
        self._task_pool.submit(start)
        return None

    def _batch_task_done(self, conn, key: tuple,
                         out: Dict[str, Any]) -> None:
        with self._lock:
            self._batch_running.discard(key)
            self._batch_done[key] = out
            while len(self._batch_done) > _BATCH_DONE_CAP:
                self._batch_done.popitem(last=False)
        self._batch_pump.add(conn, out)

    def handle_push_task(self, conn, rid, msg):
        """Execute on the leased worker; replies with the outcome. Big
        results go to the object table and return as a location; streams
        flow back as task_yield/task_result pushes."""
        msg["_t0"] = time.perf_counter()    # dispatch-phase span start
        client = self._leased(msg["lease_id"])
        return self._run_pushed_task(conn, rid, msg, client,
                                     lease_id=msg["lease_id"])

    def _run_pushed_task(self, conn, rid, msg, client, lease_id):
        spec = cloudpickle.loads(msg["spec"])
        spec.backpressure_num_objects = msg["backpressure"]
        task_hex = spec.task_id.hex()

        def release_lease(crashed: bool) -> None:
            from ray_tpu._private import worker_process as wp

            if lease_id is not None:
                with self._lock:
                    self._leases.pop(lease_id, None)
            # (the driver never calls return_worker for streams; and for
            # final outcomes its return_worker becomes a no-op.)
            # Unconditional for non-actor workers: release_worker reaps
            # dead ones itself, and skipping it would leak the checkout
            # accounting for a worker that died AFTER returning its
            # result (crash paths already called kill(), which cleared
            # the checkout — release is then a no-op on accounting).
            if client.actor_id is None:
                if crashed:
                    wp._checkout_done(client)
                else:
                    wp.release_worker(client)

        def run():
            from ray_tpu._private.worker_process import WorkerCrashed

            try:
                if _fp.ENABLED:
                    # crash arm here kills the DAEMON mid-push (node
                    # death); error arm fails just this task's push
                    _fp.fire("daemon.push_task", task=task_hex)
                t0 = msg.get("_t0")
                if t0 is not None and getattr(spec, "trace_sampled",
                                              False):
                    # dispatch phase: frame arrival -> exec request to
                    # the worker (daemon queue wait + worker acquire)
                    from ray_tpu._private import events as _events
                    now = time.perf_counter()
                    _events.record_phase(
                        self.task_events, task_id=task_hex,
                        name=spec.name, phase="dispatch",
                        dur_s=now - t0, node_id=self.node_id.hex(),
                        proc=f"daemon:{self.node_id.hex()[:8]}",
                        trace_id=getattr(spec, "trace_id", ""),
                        start_wall=_events.wall_at(t0), end_mono=now)
                wrid, pend = client._request({
                    "op": "execute_task", "fn_id": msg["fid"],
                    "args_blob": msg["args"],
                    "ctx": client._ctx_fields(spec, self.node_stub,
                                              self.runtime),
                    "runtime_env": spec.runtime_env,
                    "backpressure": msg["backpressure"],
                })
                with self._lock:
                    self._task_rids[task_hex] = (client, wrid)
                    if spec.job_id is not None:
                        # job attribution for tenant-aware OOM
                        # preemption (pruned in _memory_candidates)
                        self._task_jobs[task_hex] = spec.job_id.hex()
                outcome = client._wait_outcome(wrid, pend)
            except WorkerCrashed as e:
                client.kill(expected=False)
                with self._lock:
                    self._task_rids.pop(task_hex, None)
                release_lease(True)
                conn.reply(rid, outcome="crashed", error=str(e))
                return
            except BaseException as e:  # noqa: BLE001 — must answer HOLD
                with self._lock:
                    self._task_rids.pop(task_hex, None)
                release_lease(False)
                conn.reply_error(rid, f"{type(e).__name__}: {e}")
                return
            self._pump_outcome(conn, rid, client, spec, outcome,
                               on_done=release_lease)

        self._task_pool.submit(run)
        return rpc.HOLD

    def handle_cancel_task(self, conn, rid, msg):
        with self._lock:
            entry = self._task_rids.get(msg["task_id"])
        if entry is None:
            return {"found": False}
        client, wrid = entry
        if msg["force"]:
            client.expected_death = False
            client.proc.terminate()
        else:
            client.cancel_request(wrid)
        return {"found": True}

    def handle_gen_ack(self, conn, rid, msg):
        with self._lock:
            entry = self._task_rids.get(msg["task_id"])
        if entry is not None:
            client, wrid = entry
            try:
                client._send({"op": "gen_ack", "target": wrid})
            except Exception:
                pass
        return {"ok": True}

    # -- actors ----------------------------------------------------------
    def handle_create_actor(self, conn, rid, msg):
        spec = cloudpickle.loads(msg["spec"])

        def run():
            from ray_tpu._private import worker_process as wp

            client = wp.acquire_worker()
            client.raw_outcomes = True
            client.runtime = self.runtime
            client.node = self.node_stub
            client.actor_id = spec.actor_id
            try:
                kind, blob = client.create_actor_instance(
                    spec, self.node_stub, msg["fid"], msg["args"])
            except wp.WorkerCrashed as e:
                client.kill(expected=False)
                conn.reply(rid, outcome="crashed", error=str(e))
                return
            if kind == "err_raw":
                client.actor_id = None
                wp.release_worker(client)
                conn.reply(rid, outcome="err", blob=blob)
                return
            client.actor_since = time.time()
            wp._checkout_done(client)   # actor ownership: permanent checkout
            router = self.runtime.process_router
            with router._lock:
                router._actor_workers[spec.actor_id] = client
            actor_id = spec.actor_id
            client.add_death_callback(
                lambda c, aid=actor_id: router._actor_worker_died(aid, c))
            # targeted fast lane: actors with DEFAULT (serialized)
            # execution get a per-actor tag in the native core so
            # method calls skip the daemon's Python entirely —
            # max_concurrency>1 / concurrency-group actors keep the
            # classic thread-per-call path
            fast_tag = None
            if (self.fast_core is not None
                    and getattr(spec, "max_concurrency", 1) == 1
                    and not getattr(spec, "concurrency_groups", None)):
                try:
                    with self._lock:
                        self._fast_tag_seq += 1
                        fast_tag = self._fast_tag_seq
                    lane_host = ("127.0.0.1"
                                 if self._fast_host in ("0.0.0.0", "")
                                 else self._fast_host)
                    trid, tpend = client._request({
                        "op": "join_fast_lane",
                        "addr": [lane_host, self.fast_port],
                        "tag": fast_tag})
                    tout = client._wait_outcome(trid, tpend)
                    if tout[0] not in ("ok", "ok_raw"):
                        fast_tag = None
                except Exception:
                    fast_tag = None
            conn.reply(rid, outcome="ok", worker_pid=client.proc.pid,
                       fast_tag=fast_tag)

        self._task_pool.submit(run)
        return rpc.HOLD

    def handle_call_actor_method(self, conn, rid, msg):
        spec = cloudpickle.loads(msg["spec"])
        router = self.runtime.process_router
        with router._lock:
            client = router._actor_workers.get(spec.actor_id)
        if client is None or client.dead:
            conn.reply(rid, outcome="dead")
            return rpc.HOLD
        task_hex = spec.task_id.hex()

        def run():
            from ray_tpu._private.worker_process import WorkerCrashed

            try:
                wrid, pend = client._request({
                    "op": "call_method", "method": spec.method_name,
                    "args_blob": msg["args"],
                    "ctx": client._ctx_fields(spec, self.node_stub,
                                              self.runtime),
                    "runtime_env": spec.runtime_env,
                })
                with self._lock:
                    self._task_rids[task_hex] = (client, wrid)
                    if spec.job_id is not None:
                        self._task_jobs[task_hex] = spec.job_id.hex()
                outcome = client._wait_outcome(wrid, pend)
            except WorkerCrashed as e:
                with self._lock:
                    self._task_rids.pop(task_hex, None)
                conn.reply(rid, outcome="crashed", error=str(e))
                return
            self._pump_outcome(conn, rid, client, spec, outcome)

        self._task_pool.submit(run)
        return rpc.HOLD

    def handle_kill_actor(self, conn, rid, msg):
        actor_id = ActorID.from_hex(msg["actor_id"])
        self.runtime.process_router.discard_actor(
            actor_id, expected=msg["expected"])
        return {"ok": True}

    # -- placement group bundle 2PC --------------------------------------
    def handle_prepare_bundle(self, conn, rid, msg):
        """Phase 1: reserve (advisory ledger — placement authority is the
        single controller; the 2PC matches the reference wire contract,
        node_manager.proto PrepareBundleResources)."""
        key = (msg["pg_id"], msg["index"])
        with self._lock:
            self._bundles[key] = {"resources": msg["resources"],
                                  "state": "PREPARED"}
        return {"ok": True}

    def handle_commit_bundle(self, conn, rid, msg):
        key = (msg["pg_id"], msg["index"])
        with self._lock:
            entry = self._bundles.get(key)
            if entry is None:
                return {"ok": False}
            entry["state"] = "COMMITTED"
        return {"ok": True}

    def handle_cancel_bundle(self, conn, rid, msg):
        with self._lock:
            self._bundles.pop((msg["pg_id"], msg["index"]), None)
        return {"ok": True}

    # -- object plane -----------------------------------------------------
    def _worker_client_id(self, client) -> str:
        """Ledger identity for a pool worker: ``w:<pid>:<generation>``
        (generation disambiguates a recycled pid). The FIRST grant arms
        the crash hook — the pipe-EOF death callback fans into
        reclaim_client, covering exit, crash, and SIGKILL alike."""
        if client is None:
            return UNKNOWN_CLIENT
        cid = getattr(client, "arena_client_id", None)
        if cid is None:
            pid = getattr(getattr(client, "proc", None), "pid", 0) or 0
            cid = f"w:{pid}:{getattr(client, 'gen', 0)}"
            try:
                client.arena_client_id = cid
                client.add_death_callback(
                    lambda _c, cid=cid: self.reclaim_client(cid, "death"))
            except Exception:
                pass
        return cid

    def handle_worker_shm_op(self, call: str, kw: Dict[str, Any],
                             client=None):
        """Object-plane ops from this daemon's OWN workers, served over
        the worker pipe without touching the owner (the zero-copy
        protocol's metadata leg — payloads never ride the pipe).
        Grants and reservations are charged to the issuing worker's
        ledger identity so its death reclaims them."""
        obj = self.objects
        if call == "shm_get_meta":
            cid = self._worker_client_id(client)
            out = []
            for oid in kw["oids"]:
                entry = None
                key = obj.key_for(oid)
                if key is not None:
                    meta = obj.get_ext_meta(key, cid)  # increfs ext slot
                    if meta is not None:
                        arena, cap, off, size, slot = meta
                        entry = {"arena": arena, "capacity": cap,
                                 "off": off, "size": size, "slot": slot,
                                 "raw": obj.raw_for(key)}
                out.append(entry)
            return out
        if call == "shm_release":
            cid = self._worker_client_id(client)
            for slot in kw.get("slots", ()):
                obj.ext_release(slot, cid)
            return True
        if call == "shm_put_reserve":
            if self.pressure_level() == "hard":
                # shed NEW arena writes while hard-pressured; the
                # worker falls back to its classic put path (service
                # degrades to a payload round trip, never to an error)
                return {"full": True, "backpressure": True}
            off = obj.reserve(kw["key"], int(kw["size"]),
                              self._worker_client_id(client))
            if off is None:
                return {"full": True}
            return {"off": off}
        if call == "shm_put_seal":
            return {"ok": obj.seal(kw["key"], ref=kw.get("ref") or b"",
                                   raw=kw.get("raw"))}
        if call == "shm_put_abort":
            obj.abort_reserve(kw["key"])
            return {"ok": True}
        raise ValueError(f"unknown shm op {call!r}")

    def handle_put_object(self, conn, rid, msg):
        if self.pressure_level() == "hard":
            # typed retriable backpressure: the driver raises
            # MemoryPressureError and rides RetryPolicy until relief
            return {"backpressure": True, "level": "hard"}
        self.objects.put(msg["oid"], msg["blob"])
        key = msg["oid"]
        if key.startswith(b"put:"):
            # driver puts key by logical oid: index it so same-node
            # attached workers resolve the ref without the owner
            self.objects.register_oid(key[4:], key)
        return {"ok": True}

    def _conn_client_id(self, conn) -> str:
        """Ledger identity for an RPC client (driver or external
        attacher): minted at hello, or lazily here for attachers that
        skip it — either way connection-scoped, so on_disconnect
        reclaims everything charged to it."""
        if conn is None:
            return UNKNOWN_CLIENT
        try:
            import uuid
            return conn.meta.setdefault(
                "arena_client_id", f"c:{uuid.uuid4().hex[:12]}")
        except Exception:
            return UNKNOWN_CLIENT

    def handle_create_object(self, conn, rid, msg):
        """Reserve arena space for a same-host client's direct put (the
        client writes the payload through its own mapping, then
        seal_object). Idempotent for a retried (oid, size)."""
        if self.pressure_level() == "hard":
            return {"full": True, "backpressure": True}
        off = self.objects.reserve(msg["oid"], int(msg["size"]),
                                   self._conn_client_id(conn))
        if off is None:
            return {"full": True}
        return {"ok": True, "off": off, "arena": self.objects.arena_name,
                "capacity": (self.objects._shm.capacity()
                             if self.objects._shm else 0)}

    def handle_seal_object(self, conn, rid, msg):
        """Seal a direct-put entry (idempotent retry target: a dropped
        seal reply just re-seals). ``ref``/``raw`` feed the node-local
        oid index so attached workers resolve the object zero-copy."""
        raw = msg.get("raw")
        ok = self.objects.seal(msg["oid"], ref=msg.get("ref") or b"",
                               raw=tuple(raw) if raw else None)
        return {"ok": ok}

    def handle_get_object(self, conn, rid, msg):
        if msg["prefer_shm"]:
            # ext-slot grants only to callers that ADVERTISE the slot
            # protocol (slot_ok) — an older driver would release via
            # release_object(oid), which decrements the entry's PIN
            # ref (corrupting ownership) and leaks the slot ref
            meta = (self.objects.get_ext_meta(msg["oid"],
                                              self._conn_client_id(conn))
                    if msg.get("slot_ok") else None)
            if meta is not None:
                # ext slot ref taken on the caller's behalf: the caller
                # reads through its own mapping and drops the ref with
                # a local atomic (or release_object{slot} if its attach
                # failed) — no payload round trip, no release RPC
                arena, cap, off, size, slot = meta
                return {"shm": arena, "capacity": cap, "off": off,
                        "size": size, "slot": slot}
            ref = self.objects.get_shm_ref(msg["oid"])
            if ref is not None:
                arena, cap, off, size = ref
                return {"shm": arena, "capacity": cap, "off": off,
                        "size": size}
        blob = self.objects.get_blob(msg["oid"])
        if blob is None:
            return {"missing": True}
        return {"blob": blob}

    def handle_release_object(self, conn, rid, msg):
        if msg.get("slot") is not None:
            # ext-slot release fallback (client could not attach)
            self.objects.ext_release(int(msg["slot"]),
                                     self._conn_client_id(conn))
            return {"ok": True}
        self.objects.release(msg["oid"])
        return {"ok": True}

    def handle_free_objects(self, conn, rid, msg):
        for oid in msg["oids"]:
            self.objects.delete(oid)
        return {"ok": True}

    @rpc.concurrent
    def handle_pull_object(self, conn, rid, msg):
        """Inter-node transfer: fetch from a peer daemon into the local
        table via the PullManager (chunked + deduped + prioritized;
        reference: ObjectManager::Pull/Push). ``from_addr`` is a location
        hint; when absent (or stale) the owner's object directory is
        consulted."""
        oid = msg["oid"]
        if self.objects.contains(oid):
            return {"ok": True, "already": True}
        priority = int(msg.get("priority", PULL_PRIORITY_TASK_ARGS))
        hint = [tuple(msg["from_addr"])] if msg["from_addr"] else []
        last = {}
        tried = set()
        # Try the caller's hint first; fall back to the owner's object
        # directory when there is no hint OR the hint went stale (peer
        # evicted/died) — the directory lookup is lazy so the common
        # hinted pull pays no extra owner round-trip.
        for phase in range(2):
            candidates = hint if phase == 0 else [
                tuple(a) for a in self._locate_via_owner(oid)]
            for addr in candidates:
                if addr in tried:
                    continue
                tried.add(addr)
                pull = self.pulls.request(oid, addr, priority)
                if not pull.event.wait(timeout=120.0):
                    return {"ok": False, "error": "pull timed out"}
                if pull.ok:
                    return {"ok": True}
                last = ({"ok": False, "missing": True} if pull.missing
                        else {"ok": False, "error": pull.error})
        return last or {"ok": False, "missing": True}

    @rpc.concurrent
    def handle_push_object(self, conn, rid, msg):
        """Driver-directed proactive push of a local object to a peer
        daemon (dep prefetch, drain migration). Dedupes in flight and
        against copies the destination already holds; ``ref`` carries
        the logical ObjectID so the receiver's node-local index lets
        its attached workers resolve the pushed copy zero-copy."""
        push = self.pushes.request(msg["oid"], tuple(msg["to_addr"]),
                                   ref=msg.get("ref") or b"")
        if not push.event.wait(timeout=120.0):
            return {"ok": False, "error": "push timed out"}
        if push.ok:
            return {"ok": True, "skipped": push.skipped}
        return {"ok": False, "error": push.error}

    def handle_push_chunk(self, conn, rid, msg):
        """Receiver side of a proactive push: chunks assemble into one
        buffer; ``have`` tells the sender to stop (a pull landed it)."""
        return self.push_rx.chunk(msg["oid"], int(msg["off"]),
                                  int(msg["total"]), msg["blob"],
                                  ref=msg.get("ref") or b"",
                                  raw=msg.get("raw"))

    def handle_object_meta(self, conn, rid, msg):
        size = self.objects.nbytes_of(msg["oid"])
        if size is None:
            return {"missing": True}
        return {"size": size}

    def handle_get_object_chunk(self, conn, rid, msg):
        blob = self.objects.read_range(msg["oid"], msg["off"], msg["size"])
        if blob is None:
            return {"missing": True}
        return {"blob": blob}

    # -- cross-language tier (C++ API) ------------------------------------
    # Reference capability: `cpp/include/ray/api.h` task/actor submission
    # + `python/ray/cross_language.py` descriptors. Functions/classes are
    # exported by NAME to the head KV from Python
    # (`ray_tpu.xlang.export_task/export_actor_class`); C++ submits by
    # name with msgpack args; execution happens on this daemon's pooled
    # worker processes; results return as plain msgpack values.

    def _xlang_head(self):
        with self._lock:
            if getattr(self, "_xlang_head_client", None) is None:
                if getattr(self, "head_addr", None) is None:
                    raise RuntimeError("daemon has no head address")
                self._xlang_head_client = HeadClient(self.head_addr)
            return self._xlang_head_client

    @staticmethod
    def _xlang_plain(value):
        """Results crossing the language boundary must be msgpack-plain."""
        import numpy as _np
        if isinstance(value, (_np.integer,)):
            return int(value)
        if isinstance(value, (_np.floating,)):
            return float(value)
        if isinstance(value, _np.ndarray):
            return value.tolist()
        if isinstance(value, (list, tuple)):
            return [DaemonService._xlang_plain(v) for v in value]
        if isinstance(value, dict):
            return {str(k): DaemonService._xlang_plain(v)
                    for k, v in value.items()}
        if value is None or isinstance(value, (bool, int, float, str,
                                               bytes)):
            return value
        raise TypeError(
            f"xlang result of type {type(value).__name__} cannot cross "
            f"the language boundary; return msgpack-plain values")

    def _xlang_kv_blob(self, kind: str, name: str):
        return self._xlang_head().kv_get(
            f"xlang:{kind}:{name}".encode())

    def handle_xlang_submit(self, conn, rid, msg):
        """One-shot cross-language task on a pooled worker."""
        def run():
            from ray_tpu._private import worker_process as wp
            client = None
            streaming = False
            try:
                blob = self._xlang_kv_blob("fn", msg["name"])
                if blob is None:
                    conn.reply(rid, outcome="err",
                               error=f"no exported xlang function "
                                     f"{msg['name']!r}")
                    return
                fid = wp.register_function_blob(blob)
                spec = TaskSpec(
                    task_id=TaskID.from_random(), kind=TaskKind.NORMAL,
                    name=f"xlang:{msg['name']}", func=None)
                args_blob = cloudpickle.dumps((tuple(msg["args"]), {}))
                client = wp.acquire_worker()
                # pooled workers may carry raw_outcomes=True from a
                # prior driver-relay task — this handler decodes locally
                client.raw_outcomes = False
                client.runtime = self.runtime
                client.node = self.node_stub
                outcome = client.execute_task(spec, self.node_stub, fid,
                                              args_blob)
                if outcome[0] == "ok":
                    conn.reply(rid, outcome="ok",
                               result=self._xlang_plain(outcome[1]))
                elif outcome[0] == "gen":
                    # the worker is mid-stream: it must NOT return to
                    # the idle pool while still producing
                    streaming = True
                    conn.reply(rid, outcome="err",
                               error="xlang tasks cannot stream")
                else:
                    conn.reply(rid, outcome="err",
                               error=repr(outcome[1]))
            except BaseException as e:  # noqa: BLE001 — shipped back
                conn.reply(rid, outcome="err", error=repr(e))
            finally:
                if client is not None:
                    from ray_tpu._private import worker_process as wp
                    if streaming:
                        client.kill(expected=True)
                    wp.release_worker(client)   # reaps killed workers

        self._task_pool.submit(run)
        return rpc.HOLD

    def handle_xlang_create_actor(self, conn, rid, msg):
        """Create a Python actor (class exported by name) on a pooled
        worker, addressable by ``msg['name']`` for xlang_call_actor.
        Reuses ProcessRouter.create_actor — one copy of the checkout/
        registration protocol."""
        def run():
            from ray_tpu._private import worker_process as wp
            try:
                with self._lock:
                    taken = msg["name"] in self._xlang_actors
                if taken:
                    conn.reply(rid, outcome="err",
                               error=f"xlang actor name "
                                     f"{msg['name']!r} already taken")
                    return
                blob = self._xlang_kv_blob("actor", msg["cls"])
                if blob is None:
                    conn.reply(rid, outcome="err",
                               error=f"no exported xlang actor class "
                                     f"{msg['cls']!r}")
                    return
                fid = wp.register_function_blob(blob)
                spec = TaskSpec(
                    task_id=TaskID.from_random(),
                    kind=TaskKind.ACTOR_CREATION,
                    name=f"xlang:{msg['cls']}", func=None,
                    actor_id=ActorID.from_random(),
                    actor_name=msg["name"])
                args_blob = cloudpickle.dumps((tuple(msg["args"]), {}))
                router = self.runtime.process_router
                router.create_actor(spec, self.node_stub,
                                    (fid, args_blob))
                with self._lock:
                    lost_race = msg["name"] in self._xlang_actors
                    if not lost_race:
                        self._xlang_actors[msg["name"]] = [
                            spec.actor_id, 0, threading.Lock()]
                if lost_race:
                    # lost a concurrent create race: kill ours. The
                    # worker kill (process teardown) and the reply
                    # (wire send) both happen OUTSIDE the ledger lock.
                    with router._lock:
                        dup = router._actor_workers.pop(
                            spec.actor_id, None)
                    if dup is not None:
                        dup.kill(expected=True)
                    conn.reply(rid, outcome="err",
                               error=f"xlang actor name "
                                     f"{msg['name']!r} already taken")
                    return
                conn.reply(rid, outcome="ok",
                           actor_id=spec.actor_id.hex())
            except BaseException as e:  # noqa: BLE001 — shipped back
                conn.reply(rid, outcome="err", error=repr(e))

        self._task_pool.submit(run)
        return rpc.HOLD

    def handle_xlang_call_actor(self, conn, rid, msg):
        with self._lock:
            entry = self._xlang_actors.get(msg["name"])
        if entry is None:
            return {"outcome": "err",
                    "error": f"no xlang actor named {msg['name']!r}"}
        actor_id = entry[0]
        router = self.runtime.process_router
        with router._lock:
            client = router._actor_workers.get(actor_id)
        if client is None or client.dead:
            return {"outcome": "err", "error": "actor is dead"}

        def run():
            try:
                # Per-actor submission lock: actors guarantee
                # serialized, seqno-ordered method execution. Two C++
                # clients hitting the same named actor from different
                # pool threads must not run (or be delivered)
                # concurrently — hold the actor lock across seqno
                # assignment AND the call itself.
                with entry[2]:
                    with self._lock:
                        entry[1] += 1
                        seqno = entry[1]
                    spec = TaskSpec(
                        task_id=TaskID.from_random(),
                        kind=TaskKind.ACTOR_TASK,
                        name=f"xlang:{msg['name']}.{msg['method']}",
                        func=msg["method"], actor_id=actor_id,
                        method_name=msg["method"], seqno=seqno)
                    args_blob = cloudpickle.dumps(
                        (tuple(msg["args"]), {}))
                    outcome = client.call_method(spec, self.node_stub,
                                                 args_blob)
                # router-created actor workers run non-raw by default,
                # but tolerate raw blobs (same-language daemon decodes)
                if outcome[0] in ("ok", "ok_raw"):
                    value = (cloudpickle.loads(outcome[1])
                             if outcome[0] == "ok_raw" else outcome[1])
                    conn.reply(rid, outcome="ok",
                               result=self._xlang_plain(value))
                elif outcome[0] == "err_raw":
                    e, _tb = cloudpickle.loads(outcome[1])
                    conn.reply(rid, outcome="err", error=repr(e))
                elif outcome[0] == "err":
                    conn.reply(rid, outcome="err", error=repr(outcome[1]))
                else:
                    conn.reply(rid, outcome="err",
                               error=f"unsupported outcome {outcome[0]}")
            except BaseException as e:  # noqa: BLE001 — shipped back
                conn.reply(rid, outcome="err", error=repr(e))

        self._task_pool.submit(run)
        return rpc.HOLD

    # -- peer resource gossip (reference: ray_syncer.h:83) ---------------
    def _syncer_self_entry(self) -> Dict[str, Any]:
        with self._lock:
            running = len(self._task_rids)
        fast = (self.fast_core.stats()
                if self.fast_core is not None else {})
        return {
            "running": running + fast.get("inflight", 0)
            + fast.get("queued", 0),
            "store_used": self.objects.used_bytes(),
            "fast_queued": fast.get("queued", 0),
            # pressure level rides the load view so every driver's
            # pick_node can soft-exclude hard-pressure nodes even when
            # it never heard the direct node_pressure push
            "pressure": self.pressure_level(),
        }

    def _syncer_tick(self) -> None:
        """One anti-entropy round: refresh the self entry, exchange full
        views with <=2 random peers (merge by version), occasionally
        push the merged view to the head. Peer-to-peer propagation means
        the head needs O(1) incoming reports per interval regardless of
        node count — the RaySyncer scaling property — instead of every
        node pushing every interval."""
        import random as _random

        me = self.node_id.hex()
        # Build the self entry BEFORE taking the syncer lock: it reads
        # the daemon ledger (self._lock) and the object-store accounting
        # — nesting those under _syncer_lock stalls every concurrent
        # syncer_exchange/syncer_view handler behind store bookkeeping.
        load = self._syncer_self_entry()
        with self._syncer_lock:
            mine = self._syncer_view.get(me)
            version = (mine["v"] + 1) if mine else 1
            self._syncer_view[me] = {"v": version,
                                     "load": load,
                                     "ts": time.time()}
            view = {k: dict(v) for k, v in self._syncer_view.items()}
        peers = [(hex_id, tuple(addr))
                 for hex_id, addr in self._syncer_peers().items()
                 if hex_id != me]
        for hex_id, addr in _random.sample(peers, min(2, len(peers))):
            try:
                out = self._peer(addr).call("syncer_exchange",
                                            view=view, timeout=5.0)
                self._syncer_merge(out.get("view", {}))
            except (rpc.RpcError, OSError):
                continue
        # head push: probabilistic so ~one node per interval reports
        # (every node pushes when the cluster is tiny)
        if _random.random() < 1.0 / max(1, len(peers)):
            self._syncer_push_head()

    def _syncer_peers(self) -> Dict[str, Any]:
        """node hex -> daemon addr, from the head membership (cached)."""
        now = time.monotonic()
        if now - self._syncer_peers_ts < 5.0:
            return self._syncer_peers_cache
        try:
            head = HeadClient(self.head_addr)
            try:
                nodes = head.list_nodes()
            finally:
                head.close()
            self._syncer_peers_cache = {
                n["node_id"]: tuple(n["addr"]) for n in nodes
                if n.get("alive") and n.get("addr")}
            self._syncer_peers_ts = now
        except (OSError, rpc.RpcError):
            pass
        return self._syncer_peers_cache

    def _syncer_merge(self, view: Dict[str, Any]) -> None:
        with self._syncer_lock:
            for hex_id, entry in view.items():
                cur = self._syncer_view.get(hex_id)
                if cur is None or entry["v"] > cur["v"]:
                    self._syncer_view[hex_id] = dict(entry)

    def _syncer_push_head(self) -> None:
        try:
            head = HeadClient(self.head_addr)
            try:
                with self._syncer_lock:
                    view = {k: dict(v)
                            for k, v in self._syncer_view.items()}
                head._call("report_loads_gossip", view=view)
            finally:
                head.close()
        except (OSError, rpc.RpcError):
            pass

    def _syncer_loop(self) -> None:
        while True:
            try:
                self._syncer_tick()
            except Exception:
                pass
            time.sleep(self._syncer_interval_s)

    def handle_syncer_exchange(self, conn, rid, msg):
        self._syncer_merge(msg["view"])
        with self._syncer_lock:
            return {"view": {k: dict(v)
                             for k, v in self._syncer_view.items()}}

    def handle_syncer_view(self, conn, rid, msg):
        with self._syncer_lock:
            return {"view": {k: dict(v)
                             for k, v in self._syncer_view.items()}}

    # -- node-side OOM defense (reference: the raylet memory monitor,
    # common/memory_monitor.h + worker_killing_policy) -------------------
    def _memory_candidates(self):
        """This node's killable worker processes: push-lane running
        tasks (``self._task_rids`` — the daemon's own tracking; the
        router's ``_running`` is only the xlang path here), actor
        workers, and dedicated fast-lane workers. Task ids recorded as
        hex — that is what the driver's oom_check sends."""
        from ray_tpu._private.memory_monitor import _Candidate
        out = []
        with self._lock:
            running = dict(self._task_rids)
            # prune finished tasks' job attributions here (the one
            # periodic scan) instead of chasing every pop site
            for gone in set(self._task_jobs) - set(running):
                self._task_jobs.pop(gone, None)
            jobs = dict(self._task_jobs)
        router = self.runtime.process_router
        with router._lock:
            actors = dict(router._actor_workers)
        actor_pids = {c.proc.pid for c in actors.values()}
        for task_hex, (client, _rid) in running.items():
            if client.alive() and client.proc.pid not in actor_pids:
                out.append(_Candidate(
                    client.proc.pid, "task", task_id=task_hex,
                    retriable=True, started_at=0.0,
                    owner_key=jobs.get(task_hex, "")))
        for actor_id, client in actors.items():
            if client.alive():
                out.append(_Candidate(
                    client.proc.pid, "actor", actor_id=actor_id,
                    retriable=True, started_at=0.0, owner_key=""))
        for w in list(self._fast_workers):
            if w.alive():
                out.append(_Candidate(
                    w.proc.pid, "task", retriable=True,
                    started_at=0.0, owner_key="fast-lane"))
        return out

    def start_memory_monitor(self) -> None:
        from ray_tpu._private.config import cfg
        from ray_tpu._private.memory_monitor import (MemoryMonitor,
                                                     TenantAwarePolicy)
        if cfg().memory_monitor:
            self.memory_monitor = MemoryMonitor(
                None, candidates_fn=self._memory_candidates)
            if cfg().memory_pressure:
                # degradation order under pressure: over-quota tenants'
                # workers (driver-ledger verdict, synced) die first
                self.memory_monitor.policy = TenantAwarePolicy(
                    self.memory_monitor.policy,
                    lambda: getattr(self, "_over_quota_jobs", ()))
            self.memory_monitor.start()
        if cfg().memory_pressure:
            from ray_tpu._private.pressure import PressureController
            self.pressure = PressureController(
                self.objects,
                monitor=getattr(self, "memory_monitor", None),
                on_level=self._on_pressure_level)
            self.pressure.start()

    def pressure_level(self) -> str:
        return self.pressure.level if self.pressure is not None else "ok"

    def _on_pressure_level(self, old: str, new: str) -> None:
        """Pressure transition: tell the driver immediately (placement
        reacts now, not at the next gossip round) — the same push lane
        DRAINING uses. Gossip/heartbeats carry it to everyone else."""
        self.notify_driver("node_pressure", level=new)

    def handle_set_memory_limit(self, conn, rid, msg):
        """Driver-pushed cluster-wide limit; starts this node's monitor
        if the flag left it off."""
        from ray_tpu._private.memory_monitor import MemoryMonitor
        mon = getattr(self, "memory_monitor", None)
        if mon is None:
            mon = self.memory_monitor = MemoryMonitor(
                None, candidates_fn=self._memory_candidates,
                interval_s=0.25)
            mon.start()
        mon.limit = int(msg["limit"])
        return {"ok": True}

    def handle_oom_check(self, conn, rid, msg):
        """Did this node's monitor OOM-kill the worker running
        ``task_id`` (or, for FAST-LANE crashes only, ANY worker very
        recently — lane tasks are attributed by time, their ids live in
        the C++ core)?"""
        if _fp.ENABLED:
            _fp.fire("daemon.oom_check", task=msg.get("task_id", ""))
        mon = getattr(self, "memory_monitor", None)
        if mon is None:
            return {"oom": False, "kills": 0}
        if msg.get("task_id") and any(
                (t.hex() if hasattr(t, "hex") else t) == msg["task_id"]
                for t in mon.oom_killed_tasks):
            return {"oom": True, "kills": mon.kills}
        # the un-attributed-kill fallback applies ONLY to lane crashes
        # (their task ids live in the C++ core); a classic worker's
        # segfault inside the attribution window must not steal — and
        # consume — the lane crash's OOM entry
        if not msg.get("fast_lane"):
            return {"oom": False, "kills": mon.kills}
        # CONSUMES the entry: one kill explains one crash — it must not
        # keep painting later, unrelated crashes as OOM
        return {"oom": mon.consume_unattributed_kill(),
                "kills": mon.kills}

    # -- per-node agent (reference: dashboard/agent.py) -------------------
    def start_agent(self, host: str = "127.0.0.1") -> Optional[int]:
        """Per-node observability HTTP endpoint, served from THIS daemon
        process (the dashboard agent role: the head's dashboard answers
        cluster questions; node-local stats/profiles come from the node
        itself):
          GET /api/stats        daemon_stats as JSON
          GET /api/profile/cpu  in-process stack-sample flamegraph data
          GET /metrics          Prometheus exposition (this process)
        Returns the bound port (advertised via daemon_stats)."""
        import json as _json
        from http.server import (BaseHTTPRequestHandler,
                                 ThreadingHTTPServer)

        service = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _send(self, body: bytes, ctype: str,
                      code: int = 200) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                try:
                    path = self.path.split("?")[0].rstrip("/")
                    if path == "/api/stats":
                        with service._lock:
                            stats = {
                                "node_id": service.node_id.hex(),
                                "pid": os.getpid(),
                                "leases": len(service._leases),
                                "running": len(service._task_rids),
                            }
                        stats["store_used"] = (
                            service.objects.used_bytes())
                        if service.fast_core is not None:
                            stats["fast_lane"] = (
                                service.fast_core.stats())
                        self._send(_json.dumps(stats).encode(),
                                   "application/json")
                    elif path == "/api/profile/cpu":
                        from urllib.parse import parse_qsl

                        from ray_tpu.util.profiling import (
                            sample_cpu_profile)
                        q = dict(parse_qsl(
                            self.path.partition("?")[2]))
                        dur = min(float(q.get("duration", 2)), 30.0)
                        self._send(_json.dumps(
                            sample_cpu_profile(duration_s=dur)).encode(),
                            "application/json")
                    elif path == "/metrics":
                        from ray_tpu.util.metrics import prometheus_text
                        self._send(prometheus_text().encode(),
                                   "text/plain; version=0.0.4")
                    else:
                        self._send(b'{"error": "unknown path"}',
                                   "application/json", 404)
                except Exception as e:  # noqa: BLE001 — to the client
                    self._send(_json.dumps(
                        {"error": repr(e)}).encode(),
                        "application/json", 500)

        try:
            server = ThreadingHTTPServer((host, 0), Handler)
        except OSError:
            return None
        threading.Thread(target=server.serve_forever, daemon=True,
                         name="node-agent").start()
        self.agent_port = server.server_address[1]
        return self.agent_port

    # -- misc -------------------------------------------------------------
    def handle_core_release(self, conn, rid, msg):
        return {"ok": True}  # owner-side holds are driver-local

    def handle_daemon_ping(self, conn, rid, msg):
        return {"pid": os.getpid(), "node_id": self.node_id.hex()}

    def handle_net_chaos(self, conn, rid, msg):
        """Chaos-campaign hook: install (or clear, with an empty spec) a
        seeded netchaos registry in THIS daemon process. Programmatic
        per-node activation — the env form reaches every spawned
        process, so a schedule that must degrade ONE daemon's head link
        (partition-then-death-mark campaigns) arms it here instead."""
        from ray_tpu._private import netchaos as _nc
        spec = msg.get("spec") or ""
        if not spec:
            _nc.reset()
            return {"ok": True, "active": False}
        seed = msg.get("seed")
        _nc.activate(spec, seed=int(seed) if seed is not None else None)
        return {"ok": True, "active": True, "links": _nc.describe()}

    def handle_fail_points(self, conn, rid, msg):
        """Chaos-campaign hook, the failpoint twin of ``net_chaos``:
        install (or clear, with an empty spec) a seeded failpoint
        registry in THIS daemon process. Programmatic per-node arming —
        the env form reaches every spawned process, so a schedule that
        must pressure ONE node (``pressure.level=return(hard)``) arms
        it here instead."""
        spec = msg.get("spec") or ""
        if not spec:
            _fp.reset()
            return {"ok": True, "active": False}
        seed = msg.get("seed")
        _fp.activate(spec, seed=int(seed) if seed is not None else None)
        return {"ok": True, "active": True, "arms": _fp.describe()}

    def handle_tenancy_sync(self, conn, rid, msg):
        """Adopt the driver's per-job quota/weight table. The daemon is
        not the admission authority (dispatch gating runs driver-side,
        single-controller placement) — it mirrors the table so its own
        /metrics lane exports the cluster's quota configuration even
        when the driver is gone, and daemon_stats can show it."""
        jobs = msg.get("jobs") or {}
        self._tenancy_jobs = {str(j): dict(r) for j, r in jobs.items()}
        # over-quota jobs (driver ledger verdict): the memory monitor's
        # tenant-aware policy preempts these jobs' workers first
        self._over_quota_jobs = {str(j)
                                 for j in (msg.get("over_quota") or ())}
        for job, rec in self._tenancy_jobs.items():
            for res, cap in (rec.get("quota") or {}).get(
                    "hard", {}).items():
                _metrics.Gauge(
                    "ray_tpu_job_quota_bytes",
                    "configured hard quota caps per job and resource "
                    "axis", ("job_id", "resource")).set(
                    float(cap), tags={"job_id": job, "resource": res})
        return {"ok": True, "count": len(jobs)}

    def handle_daemon_stats(self, conn, rid, msg):
        with self._lock:
            leases = len(self._leases)
            running = len(self._task_rids)
        fast = (self.fast_core.stats()
                if self.fast_core is not None else {})
        # "running" covers BOTH planes: classic daemon-Python tasks and
        # fast-lane tasks in the native core (queued or executing)
        running += fast.get("inflight", 0) + fast.get("queued", 0)
        return {"leases": leases, "running": running,
                "store_used": self.objects.used_bytes(),
                "pull_stats": dict(self.pulls.stats),
                "push_stats": dict(self.pushes.stats),
                "push_rx_stats": dict(self.push_rx.stats),
                "arena": self.objects.arena_name,
                # grant-ledger leak observability with per-client
                # attribution (who holds a slot, is the holder alive)
                "slot_refs": self.slot_ref_attribution(),
                "fast_lane": fast,
                "agent_port": getattr(self, "agent_port", None),
                "pressure": self.pressure_level(),
                "spill": self.objects.spill_stats(),
                "actors": len(
                    self.runtime.process_router._actor_workers)}

    @rpc.concurrent
    def handle_profile_burst(self, conn, rid, msg):
        """On-demand stack-sampling burst: this daemon plus every live
        pool worker, one record per process. Blocks ~duration
        (@concurrent: runs off the connection lane)."""
        duration = max(0.1, min(float(msg.get("duration") or 2.0), 30.0))
        from ray_tpu._private import worker_process as _wp
        procs: Dict[str, Dict[str, Any]] = {}
        workers = list(_wp.live_workers())
        threads = []
        for w in workers:
            def burst_one(w=w):
                try:
                    rec = w.profile_burst(duration)
                    if isinstance(rec, dict) and rec.get("proc"):
                        procs[rec["proc"]] = rec
                except Exception:
                    pass    # a dying worker must not fail the burst
            t = threading.Thread(target=burst_one, daemon=True,
                                 name="profile-burst-worker")
            t.start()
            threads.append(t)
        own = _profiling.burst_record(
            f"daemon:{self.node_id.hex()[:8]}", duration_s=duration)
        for t in threads:
            t.join(timeout=duration + 10.0)
        procs[own["proc"]] = own
        # continuous-mode records (own sampler + result-frame ingests)
        # ride along so burst consumers see the low-rate history too
        node = _profiling.node_profile()
        for rec in (node or {}).get("procs", []):
            procs.setdefault(rec.get("proc", "?"), rec)
        return {"procs": list(procs.values())}

    def handle_daemon_stop(self, conn, rid, msg):
        def stop():
            time.sleep(0.1)
            self.runtime.process_router.shutdown()
            self.objects.close()
            os._exit(0)

        threading.Thread(target=stop, daemon=True).start()
        return {"ok": True}


# profile-flush cadence: cumulative snapshots, so a lower rate than
# spans costs nothing but staleness
_PROFILE_PUSH_S = 2.0


def _gate_profile_flush(last_push: float,
                        now: Optional[float] = None,
                        period: float = _PROFILE_PUSH_S):
    """The heartbeat's profile payload, or None (off-cadence, nothing
    sampled, or lost to the ``profile.flush`` seam). Records are
    CUMULATIVE and the head stores them with replace semantics, so the
    retry discipline is the trace.flush one: the caller advances its
    cadence stamp only on an acked beat — a dropped payload is re-sent
    (fresher) on the next beat."""
    now = time.monotonic() if now is None else now
    if now - last_push < period:
        return None
    try:
        payload = _profiling.node_profile()
    except Exception:
        return None
    if payload is not None and _fp.ENABLED:
        try:
            if _fp.fire("profile.flush",
                        procs=len(payload.get("procs", []))) is _fp.DROP:
                payload = None
        except Exception:
            payload = None
    return payload


# per-client attribution series published last beat: departed clients'
# series are removed (not left frozen at their last value) so the
# dashboard never shows a reclaimed client as still holding slots
_CLIENT_SERIES_SEEN: set = set()


def _publish_object_plane_metrics(service: DaemonService) -> None:
    """Leak + transfer observability gauges, refreshed each beat so
    they ride the metrics snapshot to the head: arena slot grants still
    referenced (a crashed client's not-yet-reclaimed grant shows up
    here, attributed to its ledger identity) and the push engine's
    cumulative/in-flight counters."""
    from ray_tpu.util.metrics import Gauge
    slots = service.slot_ref_attribution()
    g = Gauge("ray_tpu_arena_slot_refs",
              "external arena slot grants: slots still referenced "
              "('held') and total outstanding refs ('refs')",
              tag_keys=("state",))
    g.set(float(slots["held"]), tags={"state": "held"})
    g.set(float(slots["refs"]), tags={"state": "refs"})
    cg = Gauge("ray_tpu_arena_slot_clients",
               "outstanding ledger grants per client identity "
               "(alive=false rows are reclamation candidates)",
               tag_keys=("client", "alive"))
    live = set()
    for row in slots.get("clients", ()):
        alive = row.get("alive")
        tags = {"client": row["client"],
                "alive": "unknown" if alive is None else str(alive).lower()}
        cg.set(float(row["granted"]), tags=tags)
        live.add(tuple(sorted(tags.items())))
    for stale in _CLIENT_SERIES_SEEN - live:
        try:
            cg.remove(dict(stale))
        except Exception:
            pass
    _CLIENT_SERIES_SEEN.clear()
    _CLIENT_SERIES_SEEN.update(live)
    push = Gauge("ray_tpu_push_stats",
                 "object-plane push engine counters (cumulative), "
                 "tx = PushManager, rx = PushReceiver",
                 tag_keys=("side", "stat"))
    for stat, v in service.pushes.stats.items():
        push.set(float(v), tags={"side": "tx", "stat": stat})
    for stat, v in service.push_rx.stats.items():
        push.set(float(v), tags={"side": "rx", "stat": stat})
    Gauge("ray_tpu_push_inflight",
          "pushes queued or transferring right now").set(
        float(service.pushes.inflight_count()))


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--head", required=True,
                        help="host:port of the head process")
    parser.add_argument("--node-id", required=True)
    parser.add_argument("--resources", default="{}",
                        help="JSON resource map")
    parser.add_argument("--labels", default="{}")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--object-store-bytes", type=int,
                        default=256 * 1024 * 1024)
    parser.add_argument("--persist", action="store_true",
                        help="survive driver disconnects (shared cluster)")
    parser.add_argument("--announce-fd", type=int, default=-1)
    args = parser.parse_args()

    resources = json.loads(args.resources)
    from ray_tpu._private import netchaos as _nc
    _nc.set_local_role("daemon")
    service = DaemonService(args.node_id, resources,
                            args.object_store_bytes,
                            persist=args.persist, host=args.host)
    eventloop.set_proc_label(f"daemon:{args.node_id[:8]}")
    server = rpc.serve(service, host=args.host, port=0).start()
    if args.announce_fd >= 0:
        os.write(args.announce_fd, f"{server.addr[1]}\n".encode())
        os.close(args.announce_fd)

    head_host, head_port = args.head.rsplit(":", 1)
    head_addr = (head_host, int(head_port))
    service.head_addr = head_addr       # cross-language KV lookups
    threading.Thread(target=service._syncer_loop, daemon=True,
                     name="syncer-gossip").start()
    service.start_agent(host=args.host)
    service.start_memory_monitor()
    labels = json.loads(args.labels)
    head = HeadClient(head_addr)
    out = head.register_node(args.node_id, resources, labels, server.addr)
    if out.get("dead"):
        os._exit(0)     # fenced: this node_id was declared dead
    # Fencing epoch: minted by the head at every registration; stamped
    # into heartbeats and result frames so a healed partition can never
    # deliver results from a superseded incarnation.
    service.epoch = int(out.get("epoch") or 0)

    # Head-FT (reference: raylets resync after a GCS restart,
    # gcs_init_data.h): on transport failure keep re-dialing the head for
    # a grace window and re-register; only a head that stays down — or
    # one that explicitly declares us dead — ends the session.
    from ray_tpu._private.config import cfg
    grace = cfg().head_grace_s

    # Preemption watcher: SIGTERM / the maintenance-notice file trigger
    # a self-announced graceful drain (the head then fences placements,
    # the driver migrates, and the deadline escalates to node death).
    watcher = PreemptionWatcher(args.node_id, head_addr,
                                cfg().drain_deadline_s,
                                cfg().drain_notice_file)
    watcher.install_sigterm()
    watcher.start()
    service.preemption_watcher = watcher

    def reconnect() -> "HeadClient | None":
        from ray_tpu._private.retry import RetryPolicy

        if grace <= 0:
            # head FT disabled: the window is already expired
            # (RetryPolicy reads deadline_s=0 as "no deadline", which
            # would dial the dead head forever)
            return None

        def attempt() -> HeadClient:
            client = HeadClient(head_addr)
            try:
                rep = client.register_node(args.node_id, resources,
                                           labels, server.addr)
            except BaseException:
                client.close()
                raise
            if rep.get("dead"):
                client.close()
                os._exit(0)     # fenced out during the head outage
            service.epoch = int(rep.get("epoch") or service.epoch)
            return client

        try:
            return RetryPolicy.default(deadline_s=grace).run(
                attempt, loop="daemon.head_reconnect",
                retry_on=(OSError, rpc.RpcError))
        except (OSError, rpc.RpcError):
            return None     # head stayed down past the grace window

    # Observability piggyback state: span-flush cursor into this
    # daemon's TaskEventBuffer (advanced only after a delivered beat, so
    # a lost frame retries) and the metric-snapshot cadence (absolute
    # snapshots — a re-send replaces, never double-counts).
    trace_cursor = 0
    last_metrics_push = 0.0
    last_trace_push = 0.0
    last_profile_push = 0.0
    _METRICS_PUSH_S = 1.0
    _TRACE_PUSH_S = 0.5     # span-flush cadence: bounds head-store
    _TRACE_BATCH_MAX = 2000  # write rate under bursty task loads

    from ray_tpu.objectplane import tiers as _tiers

    while True:  # heartbeat loop; exit if the head declared us dead
        time.sleep(_hb_interval())
        try:
            # object-plane housekeeping: reap deferred deletes whose
            # attached-process refs dropped (external releases are
            # silent atomics), publish host-tier occupancy — the gauge
            # rides the metrics snapshot below to the head
            service.objects.reap()
            # orphan sweep: backstop for any death signal the event-
            # path reclaim missed (stale reservations, dead-pid grants,
            # ledger drift) — includes its own reap
            service.sweep_object_plane()
            service.push_rx.sweep()
            _tiers.publish_tier_bytes(_tiers.TIER_HOST,
                                      service.objects.used_bytes())
            _tiers.publish_tier_bytes(_tiers.TIER_SPILLED,
                                      service.objects.spilled_bytes())
            _publish_object_plane_metrics(service)
        except Exception:
            pass
        span_batch = []
        if time.monotonic() - last_trace_push >= _TRACE_PUSH_S:
            span_batch = service.task_events.events_after(trace_cursor)
            span_batch = span_batch[:_TRACE_BATCH_MAX]
        if span_batch and _fp.ENABLED:
            try:
                # drop/error arm = this flush is lost in transit; the
                # un-advanced cursor re-sends the batch next beat
                if _fp.fire("trace.flush",
                            n=len(span_batch)) is _fp.DROP:
                    span_batch = []
            except Exception:
                span_batch = []
        snapshot = None
        if time.monotonic() - last_metrics_push >= _METRICS_PUSH_S:
            try:
                from ray_tpu.util.metrics import export_snapshot
                snapshot = export_snapshot()
            except Exception:
                snapshot = None
        profile = _gate_profile_flush(last_profile_push)
        try:
            out = head.heartbeat(args.node_id, resources,
                                 wall_ts=time.time(),
                                 events=span_batch, metrics=snapshot,
                                 profile=profile,
                                 epoch=service.epoch)
            # advance the cursor ONLY on an acknowledged beat: an
            # "unknown" reply (restarted head, pre-re-register) returns
            # BEFORE ingesting the events — advancing would lose the
            # batch for good instead of re-sending after re-register
            if out.get("ok"):
                if span_batch:
                    trace_cursor = span_batch[-1]["seq"]
                    last_trace_push = time.monotonic()
                if snapshot is not None:
                    last_metrics_push = time.monotonic()
                if profile is not None:
                    last_profile_push = time.monotonic()
        except rpc.RpcError:
            head.close()
            new_head = reconnect()
            if new_head is None:
                os._exit(0)  # head stayed down: session over
            head = new_head
            continue
        if out.get("dead"):
            os._exit(0)
        if out.get("unknown"):
            # Restarted head with empty membership: re-register.
            try:
                out2 = head.register_node(args.node_id, resources,
                                          labels, server.addr)
            except rpc.RpcError:
                continue
            if out2.get("dead"):
                os._exit(0)     # fenced out: never rejoin as a zombie
            service.epoch = int(out2.get("epoch") or service.epoch)


if __name__ == "__main__":
    main()
