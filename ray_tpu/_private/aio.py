"""Asyncio control-plane wire: protocols on the per-process event loop.

The async twin of ``rpc.py``, selected by ``cfg().async_core`` through
``rpc.serve()`` / ``rpc.connect()``. Reference model: the C++ runtime's
asio cores (``daemon_core.cc``) — ONE event loop per process owns every
peer socket, frame parse -> handler -> reply runs pipelined on the loop,
and writes are deferred and coalesced per peer per loop iteration (the
one-sendmsg-per-peer discipline). The threaded core's per-connection
reader threads and per-frame cross-thread wakeups disappear; blocking
handlers still leave the loop (``@concurrent`` thread, FIFO lane on the
shared pool) exactly as before.

Wire parity is the contract, not an aspiration:

- Frames are byte-identical (``u32 len | msgpack map``) — async and
  threaded peers interoperate on the same socket; the ``async_core``
  hello bit only advertises the local core, it never changes framing.
- The same ``_WIRE`` counters back ``wire_metric_entries`` (imported
  from rpc, not duplicated), so dashboards don't fork per core.
- Every failpoint seam fires at the same layer: ``rpc.client.send`` /
  ``rpc.client.recv`` above the frame layer, ``rpc.server.recv`` before
  dispatch.
- netchaos sits BELOW the frame layer, but the loop must never sleep:
  the ``*_decide`` variants return ``(verdict, delay_s)`` and delays are
  served by per-connection ``call_later`` FIFO queues — a delayed frame
  holds back later frames on ITS link only, matching the threaded
  sleep's per-connection serialization without stalling other peers.

Thread-affinity: everything the loop calls is ``#: loop-only``
(raylint's loop-affinity pass + ``eventloop.assert_loop`` under the
sanitizer). Handlers run on pool/dedicated threads unless marked
``@rpc.loop_safe``; their replies re-enter the loop via
``call_soon_threadsafe`` and join the peer's next write batch.
"""

from __future__ import annotations

import asyncio
import socket
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Optional, Tuple

import msgpack

from ray_tpu._private import eventloop
from ray_tpu._private import failpoints as _fp
from ray_tpu._private import netchaos as _nc
from ray_tpu._private.rpc import (  # shared wire state: ONE set of
    # counters/schemas for both cores, so exposition and validation
    # cannot drift between them
    _LEN, _WIRE, _WIRE_LOCK, _WIRE_SERVER_REQS, _WIRE_CLIENT_REQS,
    MAX_FRAME, SEND_CONCAT_MAX, RpcError, HOLD, _validate)


def _raw_sock(transport) -> Any:
    """The real ``socket.socket`` behind a transport. asyncio hands out
    a ``TransportSocket`` facade with ``__slots__`` (not weakref-able),
    but netchaos keys link identity in a WeakKeyDictionary — unwrap to
    the underlying socket object, which is stable for the connection's
    lifetime."""
    ts = transport.get_extra_info("socket")
    return getattr(ts, "_sock", ts)


class _WriteBatcher:
    """Per-peer deferred/coalesced outbound frames.

    ``send`` never writes: it stages the frame and arms ONE
    ``call_soon`` flush, so every frame staged by the current burst of
    loop callbacks (a drained reply batch, a pump flush, fan-out to the
    same peer) leaves in a single ``transport.write`` — the
    ``daemon_core.cc`` one-sendmsg-per-peer model. Large payloads skip
    the join copy and ride their own write; adjacency is free because
    only the loop thread writes."""

    def __init__(self, loop: asyncio.AbstractEventLoop, transport,
                 sock) -> None:
        self._loop = loop
        self._transport = transport
        self._sock = sock               # chaos-link identity
        self._stage: deque = deque()
        self._armed = False
        self._delayed: deque = deque()  # (blob, due): chaos-delayed FIFO
        self._timer: Optional[asyncio.TimerHandle] = None
        self.frames = 0                 # staged frames (test hook)
        self.writes = 0                 # flush batches (test hook)

    def send(self, blob) -> None:  #: loop-only
        n = len(blob)
        if n > MAX_FRAME:
            raise RpcError(f"frame too large: {n}")
        if _nc.ENABLED:
            # chaos below the frame layer: drop suppresses the WHOLE
            # frame, dup stages the same complete frame twice, delay
            # queues it FIFO behind earlier delayed frames on this link
            verdict, delay_s = _nc.on_send_decide(self._sock, n + 4)
            if verdict is _nc.DROP_FRAME:
                return
            copies = 2 if verdict is _nc.DUP_FRAME else 1
            if delay_s > 0 or self._delayed:
                due = self._loop.time() + delay_s
                for _ in range(copies):
                    self._delayed.append((blob, due))
                self._arm_timer()
                return
            for _ in range(copies):
                self._stage_frame(blob)
            return
        self._stage_frame(blob)

    def _stage_frame(self, blob) -> None:  #: loop-only
        _WIRE["bytes_sent"] += len(blob) + 4  # lossy-tolerant plain add
        _WIRE["frames_sent"] += 1
        self.frames += 1
        self._stage.append(blob)
        if not self._armed:
            self._armed = True
            # call_soon, not an immediate write: everything staged by
            # the rest of this loop iteration joins the same flush
            self._loop.call_soon(self._flush)

    def _arm_timer(self) -> None:  #: loop-only
        if self._timer is not None:
            return
        due = self._delayed[0][1]
        self._timer = self._loop.call_later(
            max(0.0, due - self._loop.time()), self._release_delayed)

    def _release_delayed(self) -> None:  #: loop-only
        self._timer = None
        now = self._loop.time()
        while self._delayed and self._delayed[0][1] <= now:
            self._stage_frame(self._delayed.popleft()[0])
        if self._delayed:
            self._arm_timer()

    def _flush(self) -> None:  #: loop-only
        self._armed = False
        if self._transport.is_closing():
            self._stage.clear()
            return
        small: list = []
        while self._stage:
            blob = self._stage.popleft()
            n = len(blob)
            if n > SEND_CONCAT_MAX:
                # flush the joined run first so stream order holds,
                # then hand the big payload over without a concat copy
                if small:
                    self._transport.write(b"".join(small))
                    small = []
                self._transport.write(_LEN.pack(n))
                self._transport.write(bytes(blob))
                self.writes += 1
                continue
            small.append(_LEN.pack(n))
            small.append(bytes(blob))
        if small:
            self._transport.write(b"".join(small))
            self.writes += 1

    def closing(self) -> bool:
        return self._transport.is_closing()


class _FrameProtocol(asyncio.Protocol):
    """Sans-IO framing on the loop. ``owner`` (AsyncClient or
    AsyncConnection) supplies ``_attached`` / ``_on_frame`` /
    ``_on_lost`` and a ``sock`` attribute for chaos-link identity.
    Inbound chaos delays re-schedule delivery via ``call_later`` — the
    loop never sleeps — preserving per-link FIFO like the threaded
    reader's in-line sleep did."""

    def __init__(self, owner) -> None:
        self._owner = owner
        self._loop = eventloop.get_loop()
        self._buf = bytearray()
        self._in_delayed: deque = deque()  # (blob, due)
        self._in_timer: Optional[asyncio.TimerHandle] = None
        self.transport = None

    def connection_made(self, transport) -> None:  #: loop-only
        self.transport = transport
        self._owner._attached(transport)

    def data_received(self, data: bytes) -> None:  #: loop-only
        buf = self._buf
        buf += data
        off = 0
        while True:
            avail = len(buf) - off
            if avail < 4:
                break
            (n,) = _LEN.unpack_from(buf, off)
            if avail - 4 < n:
                break
            blob = bytes(buf[off + 4:off + 4 + n])
            off += 4 + n
            _WIRE["bytes_recv"] += n + 4  # lossy-tolerant plain add
            _WIRE["frames_recv"] += 1
            if _nc.ENABLED:
                verdict, delay_s = _nc.on_recv_decide(
                    self._owner.sock, n + 4)
                if verdict is _nc.DROP_FRAME:
                    continue    # inbound frame lost on the simulated link
                if delay_s > 0 or self._in_delayed:
                    self._in_delayed.append(
                        (blob, self._loop.time() + delay_s))
                    self._arm_in_timer()
                    continue
            self._deliver(blob)
        if off:
            del buf[:off]

    def _arm_in_timer(self) -> None:  #: loop-only
        if self._in_timer is not None:
            return
        due = self._in_delayed[0][1]
        self._in_timer = self._loop.call_later(
            max(0.0, due - self._loop.time()), self._release_in_delayed)

    def _release_in_delayed(self) -> None:  #: loop-only
        self._in_timer = None
        now = self._loop.time()
        while self._in_delayed and self._in_delayed[0][1] <= now:
            self._deliver(self._in_delayed.popleft()[0])
        if self._in_delayed:
            self._arm_in_timer()

    def _deliver(self, blob: bytes) -> None:  #: loop-only
        try:
            msg = msgpack.unpackb(blob, raw=False)
        except Exception:
            # protocol violation == connection death (the threaded
            # reader thread dies the same way); abort tears down via
            # connection_lost
            if self.transport is not None:
                self.transport.abort()
            return
        self._owner._on_frame(msg)

    def connection_lost(self, exc) -> None:  #: loop-only
        if self._in_timer is not None:
            self._in_timer.cancel()
            self._in_timer = None
        self._owner._on_lost(exc)


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------

class AsyncClient:
    """Duck-types ``rpc.Client``: blocking thread-side ``call`` /
    ``notify`` against a connection owned by the event loop. The socket
    is connected synchronously (constructor failure parity with the
    threaded client), then handed to the loop."""

    def __init__(self, addr: Tuple[str, int], timeout: float = 30.0,
                 on_push: Optional[Callable[[str, Dict[str, Any]], None]]
                 = None):
        self.addr = addr
        self._sock = socket.create_connection(addr, timeout=10.0)
        self._sock.settimeout(None)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.sock = self._sock          # chaos-link identity
        self._id = 0                    #: guarded by self._id_lock
        self._id_lock = threading.Lock()
        self._pending: Dict[int, list] = {}  #: guarded by self._plock
        self._plock = threading.Lock()
        self._timeout = timeout
        self._on_push = on_push
        self.dead = False
        self._loop = eventloop.get_loop()
        self._proto = _FrameProtocol(self)
        self._batcher: Optional[_WriteBatcher] = None

        async def _attach():
            await self._loop.create_connection(
                lambda: self._proto, sock=self._sock)

        eventloop.run_coro(_attach(), timeout=10.0)

    def link(self, peer_role: str, link_id: str = "") -> "AsyncClient":
        _nc.register_link(self._sock, peer_role, link_id)
        return self

    # -- loop side ----------------------------------------------------
    def _attached(self, transport) -> None:  #: loop-only
        self._batcher = _WriteBatcher(self._loop, transport, self._sock)

    def _on_frame(self, msg: Dict[str, Any]) -> None:  #: loop-only
        # Deliberately the threaded core's seam NAME: chaos schedules
        # and failpoint tests target "rpc.client.recv" and must hit
        # whichever core the process runs — one seam, two cores, so
        # the registry's one-site rule is suppressed here (and at the
        # other alternate-core sites below) rather than forking names.
        if _fp.ENABLED and _fp.fire(  # raylint: disable=failpoint-registry
                "rpc.client.recv", method=msg.get("m", "")) is _fp.DROP:
            return      # reply/push lost in transit
        rid = msg.get("i")
        if rid is None:
            # server push (no correlation id) — inline on the loop, the
            # async analogue of the threaded reader running it inline
            if self._on_push is not None:
                try:
                    self._on_push(msg.get("m", ""), msg)
                except Exception:
                    pass
            return
        with self._plock:
            slot = self._pending.pop(rid, None)
        if slot is not None:
            slot[1] = msg
            slot[0].set()

    def _on_lost(self, exc) -> None:  #: loop-only
        self._fail_all()

    def _fail_all(self) -> None:
        self.dead = True
        with self._plock:
            pending, self._pending = self._pending, {}
        for slot in pending.values():
            slot[1] = None
            slot[0].set()

    # -- thread side --------------------------------------------------
    def _send_msg(self, msg: Dict[str, Any]) -> None:
        blob = msgpack.packb(msg, use_bin_type=True)
        if self.dead or self._batcher is None:
            raise RpcError(f"connection to {self.addr} is dead")
        if eventloop.on_loop():
            # already on the loop (push handler replying): stage direct
            self._batcher.send(blob)  # raylint: disable=loop-affinity
        else:
            self._loop.call_soon_threadsafe(self._batcher.send, blob)

    def call(self, method: str, timeout: Optional[float] = None,
             **kw) -> Dict[str, Any]:
        """Blocking request/reply — THREAD context only: waiting on the
        loop thread would deadlock the wire it is waiting on."""
        if eventloop.on_loop():
            raise RuntimeError(
                f"blocking rpc call({method!r}) on the event loop "
                f"thread — hand blocking work to an executor")
        _validate(method, kw)
        if self.dead:
            raise RpcError(f"connection to {self.addr} is dead")
        with _WIRE_LOCK:
            _WIRE_CLIENT_REQS[method] = \
                _WIRE_CLIENT_REQS.get(method, 0) + 1
            _WIRE["inflight"] += 1
        try:
            return self._call_counted(method, timeout, kw)
        finally:
            with _WIRE_LOCK:
                _WIRE["inflight"] -= 1

    def _call_counted(self, method: str, timeout: Optional[float],
                      kw: Dict[str, Any]) -> Dict[str, Any]:
        # same seam discipline as the threaded client: the failpoint
        # fires BEFORE the pending slot exists, and a deadline-less
        # caller surfaces a dropped send as transport death
        dropped = (_fp.ENABLED and _fp.fire(  # raylint: disable=failpoint-registry
            "rpc.client.send", method=method) is _fp.DROP)
        if dropped and (timeout if timeout is not None
                        else self._timeout) is None:
            self._fail_all()
            raise RpcError(f"send to {self.addr} dropped by failpoint")
        with self._id_lock:
            self._id += 1
            rid = self._id
        slot = [threading.Event(), None]
        with self._plock:
            self._pending[rid] = slot
        msg = dict(kw)
        msg["m"] = method
        msg["i"] = rid
        try:
            if not dropped:
                self._send_msg(msg)
        except (OSError, RpcError):
            self._fail_all()
            raise RpcError(f"send to {self.addr} failed")
        if not slot[0].wait(timeout if timeout is not None
                            else self._timeout):
            with self._plock:
                self._pending.pop(rid, None)
            raise RpcError(f"{method} to {self.addr} timed out")
        reply = slot[1]
        if reply is None:
            raise RpcError(f"connection to {self.addr} died during "
                           f"{method}")
        if reply.get("e"):
            from ray_tpu._private.rpc import RemoteError
            raise RemoteError(reply["e"])
        return reply

    def notify(self, method: str, **kw) -> None:
        """Fire-and-forget (no reply expected)."""
        _validate(method, kw)
        if (_fp.ENABLED and _fp.fire("rpc.client.send",  # raylint: disable=failpoint-registry
                                     method=method) is _fp.DROP):
            return              # notification lost in transit
        msg = dict(kw)
        msg["m"] = method
        try:
            self._send_msg(msg)
        except (OSError, RpcError):
            self._fail_all()
            raise RpcError(f"send to {self.addr} failed")

    def close(self) -> None:
        self.dead = True

        def _close() -> None:
            t = self._proto.transport
            if t is not None:
                t.abort()

        try:
            self._loop.call_soon_threadsafe(_close)
        except RuntimeError:
            pass        # loop already torn down (interpreter exit)
        self._fail_all()    # idempotent: close() means dead for callers


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------

class AsyncConnection:
    """Duck-types ``rpc.Connection`` for services: ``sock`` / ``peer`` /
    ``meta`` / ``closed``, ``link()``, ``reply()``, ``reply_error()``,
    ``push()``. Replies may come from any thread (lane, @concurrent,
    pump); they re-enter the loop and join this peer's write batch."""

    def __init__(self, server: "AsyncServer"):
        self._server = server
        self._loop = server._loop
        self.sock = None
        self.peer = None
        self.meta: Dict[str, Any] = {}   # services stash identity here
        self.closed = False
        self._proto = _FrameProtocol(self)
        self._batcher: Optional[_WriteBatcher] = None
        # FIFO lane: identical semantics to the threaded server — from
        # one peer, ordered handlers run one at a time in arrival order
        # on the shared pool, off the loop
        self._lane: deque = deque()
        self._lane_lock = threading.Lock()
        self._lane_busy = False

    def link(self, peer_role: str, link_id: str = "") -> "AsyncConnection":
        if self.sock is not None:
            _nc.register_link(self.sock, peer_role, link_id)
        return self

    # -- loop side ----------------------------------------------------
    def _attached(self, transport) -> None:  #: loop-only
        self.sock = _raw_sock(transport)
        self.peer = transport.get_extra_info("peername")
        self._batcher = _WriteBatcher(self._loop, transport, self.sock)

    def _on_frame(self, msg: Dict[str, Any]) -> None:  #: loop-only
        self._server._dispatch(self, msg)

    def _on_lost(self, exc) -> None:  #: loop-only
        self.closed = True
        self._server._conn_lost(self)

    def _abort(self) -> None:  #: loop-only
        t = self._proto.transport
        if t is not None:
            t.abort()

    # -- any-thread reply surface ------------------------------------
    def _send(self, msg: Dict[str, Any]) -> None:
        if self.closed or self._batcher is None:
            return      # threaded parity: send-after-death marks closed
        blob = msgpack.packb(msg, use_bin_type=True)
        if eventloop.on_loop():
            self._batcher.send(blob)  # raylint: disable=loop-affinity
        else:
            self._loop.call_soon_threadsafe(self._batcher.send, blob)

    def reply(self, rid: int, **kw) -> None:
        msg = dict(kw)
        msg["i"] = rid
        self._send(msg)

    def reply_error(self, rid: int, err: str) -> None:
        self.reply(rid, e=err)

    def push(self, method: str, **kw) -> None:
        """Server-initiated message (no correlation id)."""
        msg = dict(kw)
        msg["m"] = method
        self._send(msg)


class AsyncServer:
    """Duck-types ``rpc.Server``. The listening socket is bound
    synchronously (``addr`` valid immediately, like the threaded
    server); ``start()`` hands it to the loop. Dispatch runs on the
    loop: ``@loop_safe`` handlers inline (parse -> handler -> reply
    with zero hand-offs), ``@concurrent`` on a dedicated thread,
    everything else through the per-connection FIFO lane on the shared
    pool — the same three-tier discipline as the threaded core, minus
    the per-connection reader threads."""

    def __init__(self, service: Any, host: str = "127.0.0.1",
                 port: int = 0):
        self.service = service
        self._sock = socket.create_server((host, port))
        self.addr = self._sock.getsockname()
        self._stop = False
        self._conns: list = []
        self._loop = eventloop.get_loop()
        self._aserver: Optional[asyncio.AbstractServer] = None
        from ray_tpu._private.thread_pool import DaemonThreadPool
        self._pool = DaemonThreadPool(128, name=f"rpc-{self.addr[1]}")

    def start(self) -> "AsyncServer":
        async def _start():
            return await self._loop.create_server(
                self._make_protocol, sock=self._sock)

        self._aserver = eventloop.run_coro(_start(), timeout=10.0)
        return self

    def _make_protocol(self):  #: loop-only
        conn = AsyncConnection(self)
        self._conns.append(conn)
        return conn._proto

    def _dispatch(self, conn: AsyncConnection,
                  msg: Dict[str, Any]) -> None:  #: loop-only
        method = msg.get("m", "")
        if _fp.ENABLED and _fp.fire(  # raylint: disable=failpoint-registry
                "rpc.server.recv", method=method) is _fp.DROP:
            return      # request lost before dispatch
        rid = msg.get("i")
        with _WIRE_LOCK:
            _WIRE_SERVER_REQS[method] = \
                _WIRE_SERVER_REQS.get(method, 0) + 1
        handler = getattr(self.service, f"handle_{method}", None)
        if handler is None:
            if rid is not None:
                conn.reply_error(rid, f"no such method {method!r}")
            return
        if getattr(handler, "_rpc_loop_safe", False):
            # declared non-blocking: run inline on the loop — the reply
            # (if immediate) joins this peer's coalesced write batch
            self._run_handler(conn, handler, rid, msg)
            return
        if getattr(handler, "_rpc_concurrent", False):
            # dedicated thread, NOT the shared pool (threaded parity):
            # may block for minutes without starving lane drains
            threading.Thread(
                target=self._run_handler,
                args=(conn, handler, rid, msg), daemon=True,
                name=f"rpc-conc-{method}").start()
            return
        with conn._lane_lock:
            conn._lane.append((handler, rid, msg, time.perf_counter()))
            if conn._lane_busy:
                return
            conn._lane_busy = True
        self._pool.submit(lambda: self._drain_lane(conn))

    def _run_handler(self, conn: AsyncConnection, handler, rid,
                     msg) -> None:
        try:
            out = handler(conn, rid, msg)
            if out is HOLD or rid is None:
                return
            conn.reply(rid, **(out or {}))
        except Exception as e:  # noqa: BLE001 — shipped back; the reply
            # is inside the try because an unserializable handler return
            # raises in msgpack, not in the handler
            if rid is not None:
                conn.reply_error(rid, f"{type(e).__name__}: {e}")

    def _drain_lane(self, conn: AsyncConnection) -> None:
        while True:
            with conn._lane_lock:
                if not conn._lane:
                    conn._lane_busy = False
                    return
                handler, rid, msg, t_enq = conn._lane.popleft()
            try:    # lane dwell: time queued behind same-peer requests
                from ray_tpu.util.metrics import note_queue_dwell
                note_queue_dwell("rpc.lane",
                                 time.perf_counter() - t_enq)
            except Exception:
                pass
            try:
                self._run_handler(conn, handler, rid, msg)
            except BaseException:   # never wedge the lane
                with conn._lane_lock:
                    conn._lane_busy = False
                raise

    def _conn_lost(self, conn: AsyncConnection) -> None:  #: loop-only
        try:
            self._conns.remove(conn)
        except ValueError:
            pass
        cb = getattr(self.service, "on_disconnect", None)
        if cb is not None and not self._stop:
            # service disconnect hooks may block (reclaim, persist):
            # run them off-loop, like the dying reader thread used to
            self._pool.submit(lambda: self._safe_disconnect(cb, conn))

    @staticmethod
    def _safe_disconnect(cb, conn) -> None:
        try:
            cb(conn)
        except Exception:
            pass

    def stop(self) -> None:
        self._stop = True

        def _close() -> None:
            if self._aserver is not None:
                self._aserver.close()
            for conn in list(self._conns):
                conn._abort()

        if self._aserver is None:
            # never started: the listening socket is still ours
            try:
                self._sock.close()
            except OSError:
                return
            return
        try:
            self._loop.call_soon_threadsafe(_close)
        except RuntimeError:
            pass        # loop already torn down (interpreter exit)
