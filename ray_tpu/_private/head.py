"""Head control-plane process (the GCS-server equivalent).

Reference capability: ``src/ray/gcs/gcs_server/gcs_server.h:91`` — node
membership, active health checking (``gcs_health_check_manager.h``),
internal KV (``gcs_kv_manager.h``), and long-poll pubsub
(``src/ray/pubsub/publisher.h:300``). Spawned as its own OS process
(``python -m ray_tpu._private.head``); every interaction is a typed
msgpack RPC (:mod:`ray_tpu._private.rpc`).

TPU-first division of labor: the head holds *cluster* state only — node
directory, health, KV (function table / rendezvous), pubsub. Object
ownership, scheduling authority, and lineage stay with the single
controller (the driver), which matches the SPMD model: gang placement is
decided centrally, and the accelerator data plane never crosses this
process.

Services:
- NodeInfo: register_node / heartbeat / list_nodes / drain_node;
  a monitor thread marks nodes dead after ``DEAD_AFTER_S`` without a
  heartbeat and publishes ``node_death`` (active health checking).
  ``drain_node(node_id, deadline_s, reason)`` moves the node to a
  DRAINING membership state and publishes a ``node_drain`` event so the
  scheduling authority can migrate work off it; when the deadline
  expires the monitor escalates into the ordinary death path
  (reference: the GCS DrainNode RPC + autoscaler drain protocol,
  ``gcs_node_manager.cc HandleDrainNode``). A node that was declared
  dead may NOT re-register under the same id (zombie fencing,
  mirroring the heartbeat ``{"dead": True}`` contract).
- InternalKV: kv_put / kv_get / kv_del / kv_keys (bytes in, bytes out).
- Pubsub: subscribe(channel) parks the request (long-poll HOLD); publish
  completes every parked subscriber with the event batch.

Fault tolerance (reference: GCS restart reload —
``gcs/store_client/redis_store_client.h``, ``gcs_init_data.h``): with
``--state-path`` the KV table and pubsub event logs are write-through
persisted to sqlite, so a restarted head resumes with identical KV
contents and valid pubsub cursors. Node membership is NOT persisted —
live daemons re-register themselves on their next heartbeat (the
raylet-resync model), which is the ground truth for liveness anyway.
"""

from __future__ import annotations

import argparse
import sqlite3
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import msgpack

from ray_tpu._private import failpoints as _fp
from ray_tpu._private import rpc
from ray_tpu._private.lock_sanitizer import tracked_lock
from ray_tpu._private.rpc import HOLD, Client, Connection, Server, declare

def _hb_interval() -> float:
    from ray_tpu._private.config import cfg
    return cfg().heartbeat_interval_s


def _dead_after() -> float:
    from ray_tpu._private.config import cfg
    return cfg().node_dead_after_s


# back-compat names (resolved through the central flag table)
HEARTBEAT_S = 0.2

declare("register_node", "node_id", "resources", "labels", "addr")
# heartbeat piggybacks observability: ``wall_ts`` (sender clock, for the
# head's per-node clock-offset estimate), ``events`` (daemon/worker span
# batch for the task-event store), ``metrics`` (absolute metric snapshot
# federated into the cluster /metrics view) — all optional/empty.
declare("heartbeat", "node_id", "available", "wall_ts", "events",
        "metrics", "profile", "epoch")
declare("metrics_get")
declare("profile_get")
declare("list_nodes")
declare("drain_node", "node_id", "deadline_s", "reason")
declare("mark_node_dead", "node_id", "reason")
declare("kv_put", "key", "value", "overwrite", "ns")
declare("kv_get", "key", "ns")
declare("kv_del", "key", "ns")
declare("kv_keys", "prefix", "ns")
declare("subscribe", "channel", "cursor")
declare("publish", "channel", "event")
declare("report_resources", "loads")
declare("report_loads_gossip", "view")
declare("task_events_push", "events")
declare("task_events_get", "job_id", "name", "limit")
# tenancy: persisted per-job quota/weight records (the admission
# authority's durable store) + per-job accounting federation
declare("tenancy_set", "job_id", "record")
declare("tenancy_get")
declare("tenancy_report", "jobs")
declare("head_stop")

# High-frequency gossip channels: never persisted, log trimmed to a
# window (the RaySyncer stream carries LATEST views, not history).
TRANSIENT_CHANNELS = {"resources"}
TRANSIENT_WINDOW = 200


class _NodeEntry:
    __slots__ = ("node_id", "resources", "labels", "addr", "alive",
                 "last_beat", "available", "reason", "avail_gossip_ts",
                 "draining", "drain_deadline", "drain_reason", "epoch")

    def __init__(self, node_id: str, resources: Dict[str, float],
                 labels: Dict[str, str], addr: Tuple[str, int]):
        self.node_id = node_id
        self.resources = resources
        self.labels = labels
        self.addr = addr
        self.alive = True
        self.last_beat = time.monotonic()
        self.available = dict(resources)
        self.reason = ""
        self.avail_gossip_ts = 0.0   # last syncer report for this node
        # graceful-drain state: alive + draining = no NEW placements,
        # running work may finish; past drain_deadline the monitor
        # escalates to the death path
        self.draining = False
        self.drain_deadline = 0.0    # monotonic
        self.drain_reason = ""
        # fencing epoch minted by the head at register_node; a frame
        # stamped with a LOWER epoch comes from a pre-death incarnation
        self.epoch = 0

    def view(self) -> Dict[str, Any]:
        return {"node_id": self.node_id, "resources": self.resources,
                "labels": self.labels, "addr": list(self.addr),
                "alive": self.alive, "available": self.available,
                "epoch": self.epoch,
                "reason": self.reason, "draining": self.draining,
                "drain_reason": self.drain_reason,
                "drain_deadline_s": (
                    max(0.0, self.drain_deadline - time.monotonic())
                    if self.draining else 0.0)}


class _HeadStore:
    """Write-through sqlite persistence for head state (GCS-FT role)."""

    def __init__(self, path: str):
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db.execute("PRAGMA journal_mode=WAL")
        # Writes happen under the HeadService lock: per-op fsync there
        # would stall every head RPC (incl. heartbeats) behind disk.
        # WAL + synchronous=NORMAL keeps commits memory-speed; the WAL
        # still survives a head-process crash (the FT case we replay).
        self._db.execute("PRAGMA synchronous=NORMAL")
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS kv (key BLOB PRIMARY KEY, "
            "value BLOB)")
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS events (channel TEXT, idx INTEGER, "
            "event BLOB, PRIMARY KEY(channel, idx))")
        # Head-side task-event store (reference: gcs_task_manager.h:94):
        # task state transitions buffered by drivers land here so the
        # state API / timeline survive driver exit. Bounded by row count.
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS task_events ("
            "seq INTEGER PRIMARY KEY AUTOINCREMENT, "
            "task_id TEXT, name TEXT, event TEXT, job_id TEXT, "
            "wall_ts REAL, payload BLOB)")
        self._db.commit()

    def append_task_events(self, events: List[Dict[str, Any]],
                           max_rows: int) -> None:
        self._db.executemany(
            "INSERT INTO task_events "
            "(task_id, name, event, job_id, wall_ts, payload) "
            "VALUES (?, ?, ?, ?, ?, ?)",
            [(ev.get("task_id", ""), ev.get("name", ""),
              ev.get("event", ""), ev.get("job_id", ""),
              ev.get("wall_ts", 0.0),
              msgpack.packb(ev, use_bin_type=True))
             for ev in events])
        # bounded: drop the oldest rows past the cap (one statement,
        # amortized — gcs_task_manager evicts the same way)
        self._db.execute(
            "DELETE FROM task_events WHERE seq <= ("
            "SELECT MAX(seq) FROM task_events) - ?", (max_rows,))
        self._db.commit()

    def get_task_events(self, job_id: str = "", name: str = "",
                        limit: int = 10_000) -> List[Dict[str, Any]]:
        q = "SELECT payload FROM task_events"
        cond, args = [], []
        if job_id:
            cond.append("job_id = ?")
            args.append(job_id)
        if name:
            cond.append("name = ?")
            args.append(name)
        if cond:
            q += " WHERE " + " AND ".join(cond)
        q += " ORDER BY seq DESC LIMIT ?"
        args.append(int(limit))
        rows = self._db.execute(q, args).fetchall()
        out = [msgpack.unpackb(r[0], raw=False) for r in rows]
        out.reverse()
        return out

    def load(self) -> Tuple[Dict[bytes, bytes], Dict[str, List[Any]]]:
        kv = {bytes(k): bytes(v) for k, v in
              self._db.execute("SELECT key, value FROM kv")}
        events: Dict[str, List[Any]] = {}
        for chan, idx, blob in self._db.execute(
                "SELECT channel, idx, event FROM events "
                "ORDER BY channel, idx"):
            events.setdefault(chan, []).append(
                msgpack.unpackb(blob, raw=False))
        return kv, events

    def put(self, key: bytes, value: bytes) -> None:
        self._db.execute("INSERT OR REPLACE INTO kv VALUES (?, ?)",
                         (key, value))
        self._db.commit()

    def delete(self, key: bytes) -> None:
        self._db.execute("DELETE FROM kv WHERE key = ?", (key,))
        self._db.commit()

    def append_event(self, channel: str, idx: int, event: Any) -> None:
        self._db.execute(
            "INSERT OR REPLACE INTO events VALUES (?, ?, ?)",
            (channel, idx, msgpack.packb(event, use_bin_type=True)))
        self._db.commit()


# Persisted drain records live in the head store's kv table under this
# raw prefix. Client-visible keys are stored as ``ns + b":" + key`` —
# they ALWAYS contain a colon — so a colon-free prefix can never collide
# with (or leak into) any namespace's kv_get/kv_keys view.
_DRAIN_KEY = b"\x00drain\x00"
# Per-job tenancy records (quota/weight) persist under the same
# colon-free raw-prefix scheme: ``--state-path`` survives head respawn,
# so quotas outlive both the head process and the submitting driver.
_TENANCY_KEY = b"\x00tenancy\x00"
# Per-node fencing epochs persist under the same colon-free raw-prefix
# scheme: epochs must be monotonic ACROSS head restarts, or a healed
# pre-restart zombie could stamp frames the fence accepts.
_EPOCH_KEY = b"\x00epoch\x00"


class HeadService:
    def __init__(self, state_path: Optional[str] = None):
        self._lock = tracked_lock("head.state", reentrant=False)
        self._nodes: Dict[str, _NodeEntry] = {}  #: guarded by self._lock
        self._kv: Dict[bytes, bytes] = {}        #: guarded by self._lock
        # pubsub: channel -> (event log, parked subscriber conns)
        self._events: Dict[str, List[Any]] = {}  #: guarded by self._lock
        #: guarded by self._lock
        self._bases: Dict[str, int] = {}   # trimmed-channel log offsets
        #: guarded by self._lock
        self._parked: Dict[str, List[Tuple[Connection, int, int]]] = {}
        self._store: Optional[_HeadStore] = None
        # task-event store: sqlite when persistent, bounded ring in
        # memory otherwise (reference: gcs_task_manager.h:94)
        self._task_events_cap = 100_000
        # per-node load entries converged via daemon peer gossip
        # (report_loads_gossip); versioned like the daemons' own views
        self._gossip_loads: Dict[str, Dict[str, Any]] = {}  #: guarded by self._lock
        from collections import deque as _deque
        self._task_events: Any = _deque(maxlen=self._task_events_cap)
        # metrics federation: node_id -> latest absolute metric snapshot
        # shipped on that daemon's heartbeat (snapshot REPLACE, so a
        # re-sent frame never double-counts); per-node clock offset
        # (head wall - daemon wall) estimated from the same heartbeats.
        #: guarded by self._lock
        self._node_metrics: Dict[str, List[Dict[str, Any]]] = {}
        self._node_clock_off: Dict[str, float] = {}  #: guarded by self._lock
        # profile federation: node_id -> latest CUMULATIVE profile
        # payload off that daemon's heartbeat (replace semantics — the
        # counters only grow, so the newest payload supersedes all)
        #: guarded by self._lock
        self._node_profiles: Dict[str, Dict[str, Any]] = {}
        # node_id -> (wall-clock deadline, reason): drains survive a
        # head restart (membership does not, so the record re-attaches
        # when the draining daemon re-registers after the respawn).
        self._drains: Dict[str, Tuple[float, str]] = {}  #: guarded by self._lock
        # tenancy: job -> {"weight": .., "quota": {"hard": .., "soft": ..}}
        # (persisted) and job -> latest reported usage row (replace
        # semantics — each driver report supersedes its previous one).
        self._tenancy: Dict[str, Dict[str, Any]] = {}  #: guarded by self._lock
        self._tenancy_usage: Dict[str, Dict[str, Any]] = {}  #: guarded by self._lock
        # node_id -> last minted fencing epoch (persisted: epochs stay
        # monotonic across a head restart even though membership resets)
        self._node_epochs: Dict[str, int] = {}  #: guarded by self._lock
        if state_path:
            self._store = _HeadStore(state_path)
            self._kv, self._events = self._store.load()
            for key in [k for k in self._kv if k.startswith(_DRAIN_KEY)]:
                blob = self._kv.pop(key)
                try:
                    rec = msgpack.unpackb(blob, raw=False)
                    self._drains[key[len(_DRAIN_KEY):].decode()] = (
                        float(rec["deadline_wall"]), str(rec["reason"]))
                except Exception:
                    # a malformed record must not keep the head down
                    self._store.delete(key)
            for key in [k for k in self._kv
                        if k.startswith(_TENANCY_KEY)]:
                blob = self._kv.pop(key)
                try:
                    self._tenancy[key[len(_TENANCY_KEY):].decode()] = (
                        msgpack.unpackb(blob, raw=False))
                except Exception:
                    self._store.delete(key)
            for key in [k for k in self._kv
                        if k.startswith(_EPOCH_KEY)]:
                blob = self._kv.pop(key)
                try:
                    self._node_epochs[key[len(_EPOCH_KEY):].decode()] = (
                        int(blob))
                except Exception:
                    self._store.delete(key)
        self._stop = threading.Event()
        self._monitor = threading.Thread(target=self._health_loop,
                                         daemon=True, name="head-health")
        self._monitor.start()

    # -- node membership / health ---------------------------------------
    def handle_register_node(self, conn, rid, msg):
        node_id = msg["node_id"]
        entry = _NodeEntry(node_id, msg["resources"],
                           msg["labels"], tuple(msg["addr"]))
        with self._lock:
            cur = self._nodes.get(node_id)
            if cur is not None and not cur.alive:
                # Zombie fencing: this node was declared dead (death
                # published, owners already recovered its work); a
                # re-registration would resurrect it with stale state.
                # Same contract as the heartbeat {"dead": True} reply —
                # the daemon must exit.
                return {"ok": False, "dead": True, "reason": cur.reason}
            drain = self._drains.get(node_id)
            if drain is not None:
                # A drain survived a head restart: re-attach it with the
                # remaining wall-clock window.
                entry.draining = True
                entry.drain_deadline = time.monotonic() + max(
                    0.0, drain[0] - time.time())
                entry.drain_reason = drain[1]
            # Mint a monotonic fencing epoch for this incarnation:
            # bumped on EVERY register (a re-registration after a head
            # restart or death-mark gets a strictly higher epoch), and
            # persisted so epochs survive head respawn. Drivers fence
            # result frames stamped with an older epoch.
            epoch = self._node_epochs.get(node_id, 0) + 1
            self._node_epochs[node_id] = epoch
            entry.epoch = epoch
            if self._store is not None:
                self._store.put(_EPOCH_KEY + node_id.encode(),
                                str(epoch).encode())
            self._nodes[node_id] = entry
        conn.meta["node_id"] = node_id
        conn.link("daemon", node_id)
        self._publish("node", {"kind": "added", "node": entry.view()})
        if entry.draining:
            # re-announce so a (re)subscribed driver resumes migration
            self._publish("node", {
                "kind": "drain", "node_id": node_id,
                "deadline_s": max(0.0, entry.drain_deadline
                                  - time.monotonic()),
                "reason": entry.drain_reason})
        return {"ok": True, "draining": entry.draining,
                "epoch": entry.epoch}

    def handle_heartbeat(self, conn, rid, msg):
        node_id = msg["node_id"]
        # clock-offset estimate (head wall - daemon wall at receipt; the
        # half-RTT error is negligible next to cross-host clock skew):
        # applied to every span the daemon flushes so the merged timeline
        # shares ONE timebase.
        off = 0.0
        wall = float(msg.get("wall_ts") or 0.0)
        if wall:
            off = time.time() - wall
        with self._lock:
            entry = self._nodes.get(node_id)
            if entry is None:
                return {"ok": False, "unknown": True}
            ep = msg.get("epoch")
            if ep is not None and ep and entry.epoch and ep < entry.epoch:
                # Stale-epoch beat: a NEWER incarnation of this node_id
                # has registered since this sender's epoch was minted.
                # The zombie must exit — and its beat must not refresh
                # the live incarnation's liveness.
                return {"ok": False, "dead": True, "stale_epoch": True}
            entry.last_beat = time.monotonic()
            # The daemon's heartbeat carries its STATIC resources; the
            # driver's syncer gossip carries the true availability.
            # Gossip wins while fresh; heartbeat repopulates once the
            # reporting driver goes quiet (left / crashed).
            if time.monotonic() - entry.avail_gossip_ts > 2.0:
                entry.available = msg["available"]
            was_dead = not entry.alive
            draining = entry.draining
            if wall:
                self._node_clock_off[node_id] = off
            snapshot = msg.get("metrics")
            if snapshot is not None:
                self._node_metrics[node_id] = snapshot
            profile = msg.get("profile")
            if profile is not None:
                self._node_profiles[node_id] = profile
        if was_dead:
            # A heartbeat from a node we declared dead: tell it to exit
            # (reference: raylets that lost GCS contact must not rejoin
            # with stale state).
            return {"ok": False, "dead": True}
        events = msg.get("events") or []
        if events:
            for ev in events:
                if off:
                    ev["wall_ts"] = ev.get("wall_ts", 0.0) + off
                    if "start_wall" in ev:
                        ev["start_wall"] = ev["start_wall"] + off
                ev["clock_off"] = off
                ev.setdefault("node_id", node_id)
            self._ingest_task_events(events)
        return {"ok": True, "draining": draining,
                "head_wall": time.time()}

    def handle_metrics_get(self, conn, rid, msg):
        """Federated per-node metric snapshots (daemon heartbeats)."""
        with self._lock:
            return {"nodes": {nid: snap for nid, snap
                              in self._node_metrics.items()}}

    def handle_profile_get(self, conn, rid, msg):
        """Federated per-node profile payloads (daemon heartbeats) plus
        the head's own continuous-sampler record."""
        with self._lock:
            nodes = dict(self._node_profiles)
        try:
            from ray_tpu.util import profiling as _profiling
            own = _profiling.process_profile()
        except Exception:
            own = None
        return {"nodes": nodes, "head": own}

    def handle_list_nodes(self, conn, rid, msg):
        with self._lock:
            nodes = [e.view() for e in self._nodes.values()]
            for n in nodes:
                g = self._gossip_loads.get(n["node_id"])
                if g is not None:
                    n["gossip_load"] = g["load"]
                    n["gossip_version"] = g["v"]
            return {"nodes": nodes}

    def handle_drain_node(self, conn, rid, msg):
        """Graceful drain: publish ``node_drain`` and keep the node
        alive-but-DRAINING so running work can finish and the driver can
        migrate objects/actors off it; the health monitor escalates to
        the death path when ``deadline_s`` expires. (The former behavior
        — an immediate ``_mark_dead`` — made every planned departure as
        expensive as a crash.)"""
        node_id = msg["node_id"]
        deadline_s = max(0.0, float(msg.get("deadline_s") or 0.0))
        reason = msg.get("reason") or "drain"
        with self._lock:
            entry = self._nodes.get(node_id)
            if entry is None or not entry.alive:
                return {"ok": False, "unknown": True}
            if entry.draining:
                # idempotent: the first drain's deadline stands
                return {"ok": True, "already": True}
            entry.draining = True
            entry.drain_deadline = time.monotonic() + deadline_s
            entry.drain_reason = reason
            self._drains[node_id] = (time.time() + deadline_s, reason)
            if self._store is not None:
                self._store.put(
                    _DRAIN_KEY + node_id.encode(),
                    msgpack.packb({"deadline_wall": time.time()
                                   + deadline_s,
                                   "reason": reason},
                                  use_bin_type=True))
        self._publish("node", {"kind": "drain", "node_id": node_id,
                               "deadline_s": deadline_s,
                               "reason": reason})
        return {"ok": True}

    def handle_mark_node_dead(self, conn, rid, msg):
        # The driver observed a daemon failure directly (RPC error) and
        # reports it before the health window elapses.
        self._mark_dead(msg["node_id"], msg["reason"])
        return {"ok": True}

    def _mark_dead(self, node_id: str, reason: str,
                   drain_expired: bool = False) -> None:
        with self._lock:
            entry = self._nodes.get(node_id)
            if entry is None or not entry.alive:
                return
            entry.alive = False
            entry.reason = reason
            was_draining = entry.draining
            entry.draining = False
            self._drains.pop(node_id, None)
            # a dead node's last metric snapshot must not keep being
            # served as live by the cluster /metrics federation (and
            # the dicts must not grow forever under node churn)
            self._node_metrics.pop(node_id, None)
            self._node_clock_off.pop(node_id, None)
            self._node_profiles.pop(node_id, None)
            if self._store is not None:
                self._store.delete(_DRAIN_KEY + node_id.encode())
        self._publish("node", {"kind": "death", "node_id": node_id,
                               "reason": reason,
                               "was_draining": was_draining,
                               "drain_expired": drain_expired})

    def _health_loop(self) -> None:
        while not self._stop.wait(_hb_interval()):
            now = time.monotonic()
            dead: List[str] = []
            expired: List[str] = []
            window = _dead_after()
            with self._lock:
                for entry in self._nodes.values():
                    if not entry.alive:
                        continue
                    if now - entry.last_beat > window:
                        dead.append(entry.node_id)
                    elif entry.draining and now > entry.drain_deadline:
                        expired.append(entry.node_id)
            for node_id in dead:
                self._mark_dead(node_id, "missed heartbeats")
            for node_id in expired:
                # escalation: the drain window closed with the node
                # still up — fall back to the ordinary death path
                # (lineage reconstruction covers whatever did not
                # migrate in time)
                self._mark_dead(node_id, "drain deadline expired",
                                drain_expired=True)

    def on_disconnect(self, conn: Connection) -> None:
        node_id = conn.meta.get("node_id")
        if node_id:
            self._mark_dead(node_id, "connection lost")
        # drop parked long-polls from this conn
        with self._lock:
            for parked in self._parked.values():
                parked[:] = [p for p in parked if p[0] is not conn]

    # -- internal KV -----------------------------------------------------
    # -- tenancy: quota store + per-job accounting federation -----------
    def handle_tenancy_set(self, conn, rid, msg):
        """Upsert one job's quota/weight record (persisted)."""
        job = str(msg["job_id"])
        record = msg.get("record") or {}
        with self._lock:
            self._tenancy[job] = record
            if self._store is not None:
                self._store.put(_TENANCY_KEY + job.encode(),
                                msgpack.packb(record, use_bin_type=True))
        return {"ok": True}

    def handle_tenancy_get(self, conn, rid, msg):
        """All job records, with the latest federated usage merged in."""
        with self._lock:
            jobs = {j: dict(r) for j, r in self._tenancy.items()}
            for j, usage in self._tenancy_usage.items():
                jobs.setdefault(j, {})["usage"] = usage
        return {"jobs": jobs}

    def handle_tenancy_report(self, conn, rid, msg):
        """Per-job accounting federation (replace semantics per job)."""
        jobs = msg.get("jobs") or {}
        with self._lock:
            for j, row in jobs.items():
                self._tenancy_usage[str(j)] = row
        return {"ok": True, "count": len(jobs)}

    def handle_kv_put(self, conn, rid, msg):
        if _fp.ENABLED:
            # crash arm = head dies mid-put (the respawn/redial drill);
            # error arm surfaces as a RemoteError at the caller
            _fp.fire("head.kv_put")
        key = msg["ns"] + b":" + msg["key"]
        with self._lock:
            if not msg["overwrite"] and key in self._kv:
                return {"added": False}
            self._kv[key] = msg["value"]
            if self._store is not None:
                self._store.put(key, msg["value"])
        return {"added": True}

    def handle_kv_get(self, conn, rid, msg):
        with self._lock:
            value = self._kv.get(msg["ns"] + b":" + msg["key"])
        return {"value": value}

    def handle_kv_del(self, conn, rid, msg):
        key = msg["ns"] + b":" + msg["key"]
        with self._lock:
            self._kv.pop(key, None)
            if self._store is not None:
                self._store.delete(key)
        return {"ok": True}

    def handle_kv_keys(self, conn, rid, msg):
        pre = msg["ns"] + b":" + msg["prefix"]
        nslen = len(msg["ns"]) + 1
        with self._lock:
            keys = [k[nslen:] for k in self._kv if k.startswith(pre)]
        return {"keys": keys}

    # -- long-poll pubsub -------------------------------------------------
    def handle_subscribe(self, conn, rid, msg):
        """Long-poll: reply immediately if the cursor is behind, else park
        until the next publish (reference: long_poll.py:70,222 — clients
        hold a request open and the host completes it on change)."""
        channel, cursor = msg["channel"], msg["cursor"]
        with self._lock:
            log = self._events.setdefault(channel, [])
            base = self._bases.get(channel, 0)
            total = base + len(log)
            if cursor < total:
                start = max(0, cursor - base)  # trimmed past: skip ahead
                return {"events": log[start:], "cursor": total}
            self._parked.setdefault(channel, []).append(
                (conn, rid, cursor))
        return HOLD

    def _publish(self, channel: str, event: Any) -> None:
        if _fp.ENABLED and _fp.fire("head.pubsub_publish",
                                    channel=channel) is _fp.DROP:
            return      # event lost before the log (subscribers starve)
        with self._lock:
            log = self._events.setdefault(channel, [])
            log.append(event)
            if channel in TRANSIENT_CHANNELS:
                if len(log) > TRANSIENT_WINDOW:  # keep only the window
                    drop = len(log) - TRANSIENT_WINDOW
                    del log[:drop]
                    self._bases[channel] = \
                        self._bases.get(channel, 0) + drop
            elif self._store is not None:
                self._store.append_event(channel, len(log) - 1, event)
            parked = self._parked.pop(channel, [])
            base = self._bases.get(channel, 0)
            cursor = base + len(log)
        for conn, rid, start in parked:
            conn.reply(rid, events=log[max(0, start - base):],
                       cursor=cursor)

    def handle_publish(self, conn, rid, msg):
        self._publish(msg["channel"], msg["event"])
        return {"ok": True}

    # -- task events (reference: gcs_task_manager.h:94) ------------------
    def _ingest_task_events(self, events: List[Dict[str, Any]]) -> None:
        with self._lock:
            if self._store is not None:
                self._store.append_task_events(events,
                                               self._task_events_cap)
            else:
                self._task_events.extend(events)

    def handle_task_events_push(self, conn, rid, msg):
        events = msg["events"]
        self._ingest_task_events(events)
        return {"ok": True, "count": len(events)}

    def handle_task_events_get(self, conn, rid, msg):
        job_id = msg.get("job_id") or ""
        name = msg.get("name") or ""
        limit = int(msg.get("limit") or 10_000)
        with self._lock:
            if self._store is not None:
                out = self._store.get_task_events(job_id, name, limit)
            else:
                out = [ev for ev in self._task_events
                       if (not job_id or ev.get("job_id") == job_id)
                       and (not name or ev.get("name") == name)]
                out = out[-limit:]
        return {"events": out}

    def handle_report_resources(self, conn, rid, msg):
        """Resource-view gossip (the RaySyncer role,
        ``common/ray_syncer/ray_syncer.h:83``): the scheduling authority
        pushes per-node availability; the head updates its membership
        view and re-broadcasts on the transient 'resources' channel so
        any subscriber (state API, autoscaler, other drivers) converges
        on the same cluster view without polling."""
        updated = {}
        with self._lock:
            for node_hex, avail in msg["loads"].items():
                entry = self._nodes.get(node_hex)
                if entry is not None and entry.alive:
                    entry.available = dict(avail)
                    entry.avail_gossip_ts = time.monotonic()
                    updated[node_hex] = dict(avail)
        if updated:
            self._publish("resources", {"available": updated})
        return {"ok": True}

    def handle_report_loads_gossip(self, conn, rid, msg):
        """Peer-gossip ingestion (reference: ray_syncer.h:83): ONE node
        per gossip interval pushes the cluster-wide merged view it
        converged on — the head never needs per-node load reports, so
        its inbound load-report rate is O(1) in cluster size."""
        with self._lock:
            for node_hex, entry in msg["view"].items():
                cur = self._gossip_loads.get(node_hex)
                if cur is None or entry["v"] > cur["v"]:
                    self._gossip_loads[node_hex] = dict(entry)
        return {"ok": True}

    def handle_head_stop(self, conn, rid, msg):
        self._stop.set()
        threading.Thread(target=lambda: (time.sleep(0.1),
                                         __import__("os")._exit(0)),
                         daemon=True).start()
        return {"ok": True}


class HeadClient:
    """Typed client for head services, with a background subscriber.

    ``reconnect_window`` > 0 makes every call transparently re-dial the
    head for up to that many seconds on transport failure — the driver's
    survival path across a head restart (reference: GCS client retries,
    ``gcs/gcs_client``).
    """

    def __init__(self, addr: Tuple[str, int], reconnect_window: float = 0.0):
        self._client = rpc.connect(addr).link("head")
        self.addr = addr
        self._reconnect_window = reconnect_window
        self._dial_lock = tracked_lock("head_client.dial",
                                       reentrant=False)
        self._sub_stop = threading.Event()
        self._sub_threads: List[threading.Thread] = []
        # live per-channel subscriber connections, tracked so close()
        # can actually close them (a parked long-poll otherwise holds
        # its socket open forever)
        self._sub_clients: List[Client] = []  #: guarded by self._sub_lock
        self._sub_lock = tracked_lock("head_client.subs",
                                      reentrant=False)
        self._retry_policy = None   # built lazily; immutable once made

    def _redial(self) -> None:
        with self._dial_lock:
            if not self._client.dead:
                return
            # raises OSError while head is down. _dial_lock exists to
            # single-flight this dial — concurrent callers must wait
            # for ONE reconnect, not race N of them — so holding it
            # across the connect is the lock's entire purpose.
            client = rpc.connect(self.addr).link("head")  # raylint: disable=blocking-under-lock
            old, self._client = self._client, client
            old.close()

    def _call(self, method: str, timeout: Optional[float] = None, **kw):
        if self._reconnect_window <= 0:
            return self._client.call(method, timeout=timeout, **kw)
        if self._retry_policy is None:
            # built once: cfg() reads + dataclass construction must not
            # ride every head RPC on the control-plane hot path
            from ray_tpu._private.retry import RetryPolicy
            self._retry_policy = RetryPolicy.default(
                deadline_s=self._reconnect_window)

        def attempt():
            try:
                # the client may have been swapped by a redial; read it
                # fresh each attempt
                return self._client.call(method, timeout=timeout, **kw)
            except rpc.RpcError:
                try:
                    self._redial()
                except OSError:
                    pass        # head still down: next attempt retries
                raise

        return self._retry_policy.run(
            attempt, loop="head.redial", retry_on=(rpc.RpcError,))

    # node info
    def register_node(self, node_id: str, resources: Dict[str, float],
                      labels: Dict[str, str], addr: Tuple[str, int]):
        return self._call("register_node", node_id=node_id,
                          resources=resources, labels=labels,
                          addr=list(addr))

    def heartbeat(self, node_id: str, available: Dict[str, float],
                  wall_ts: float = 0.0,
                  events: Optional[List[Dict[str, Any]]] = None,
                  metrics: Optional[List[Dict[str, Any]]] = None,
                  profile: Optional[Dict[str, Any]] = None,
                  epoch: int = 0):
        return self._call("heartbeat", node_id=node_id,
                          available=available, wall_ts=wall_ts,
                          events=events or [], metrics=metrics,
                          profile=profile, epoch=epoch, timeout=5.0)

    def metrics_get(self) -> Dict[str, List[Dict[str, Any]]]:
        """node_id -> latest federated metric snapshot. Bounded: a
        wedged head must not hang a dashboard scrape thread forever."""
        return self._call("metrics_get", timeout=5.0)["nodes"]

    def profile_get(self) -> Dict[str, Any]:
        """{"nodes": node_id -> federated profile payload, "head": the
        head's own record or None}."""
        return self._call("profile_get", timeout=5.0)

    def list_nodes(self) -> List[Dict[str, Any]]:
        return self._call("list_nodes")["nodes"]

    def task_events_push(self, events: List[Dict[str, Any]]) -> int:
        return self._call("task_events_push",
                          events=events)["count"]

    def task_events_get(self, job_id: str = "", name: str = "",
                        limit: int = 10_000) -> List[Dict[str, Any]]:
        return self._call("task_events_get", job_id=job_id, name=name,
                          limit=limit)["events"]

    def mark_node_dead(self, node_id: str, reason: str) -> None:
        self._call("mark_node_dead", node_id=node_id, reason=reason)

    def drain_node(self, node_id: str, deadline_s: float,
                   reason: str = "drain") -> Dict[str, Any]:
        """Ask the head to move a node into the DRAINING state (graceful
        departure); escalates to the death path after ``deadline_s``."""
        return self._call("drain_node", node_id=node_id,
                          deadline_s=deadline_s, reason=reason)

    def report_resources(self, loads: Dict[str, Dict[str, float]]) -> None:
        """Push per-node availability views (syncer gossip)."""
        self._call("report_resources", loads=loads, timeout=5.0)

    # tenancy (fair-share quota store + accounting federation)
    def tenancy_set(self, job_id: str, record: Dict[str, Any]) -> None:
        self._call("tenancy_set", job_id=job_id, record=record,
                   timeout=5.0)

    def tenancy_get(self) -> Dict[str, Dict[str, Any]]:
        return self._call("tenancy_get", timeout=5.0)["jobs"]

    def tenancy_report(self, jobs: Dict[str, Any]) -> None:
        self._call("tenancy_report", jobs=jobs, timeout=5.0)

    # kv
    def kv_put(self, key: bytes, value: bytes, overwrite: bool = True,
               namespace: bytes = b"") -> bool:
        return self._call("kv_put", key=key, value=value,
                          overwrite=overwrite, ns=namespace)["added"]

    def kv_get(self, key: bytes, namespace: bytes = b"") -> Optional[bytes]:
        return self._call("kv_get", key=key, ns=namespace)["value"]

    def kv_del(self, key: bytes, namespace: bytes = b"") -> None:
        self._call("kv_del", key=key, ns=namespace)

    def kv_keys(self, prefix: bytes = b"",
                namespace: bytes = b"") -> List[bytes]:
        return self._call("kv_keys", prefix=prefix, ns=namespace)["keys"]

    # pubsub
    def _sub_swap(self, old: Optional[Client],
                  new: Optional[Client]) -> None:
        """Track the live subscriber connection for close(). If close()
        already ran, the fresh client is closed on the spot (the dial
        won the race with stop)."""
        with self._sub_lock:
            if old is not None:
                try:
                    self._sub_clients.remove(old)
                except ValueError:
                    pass
            if new is not None:
                self._sub_clients.append(new)
                if self._sub_stop.is_set():
                    new.close()

    def subscribe(self, channel: str, callback) -> None:
        """Long-poll subscription: dedicated connection per channel (a
        parked poll must not block other requests' replies)."""
        def loop():
            cursor = 0
            try:
                sub = rpc.connect(self.addr, timeout=None)
            except OSError:
                return
            self._sub_swap(None, sub)
            try:
                while not self._sub_stop.is_set():
                    try:
                        out = sub.call("subscribe", channel=channel,
                                       cursor=cursor, timeout=None)
                    except rpc.RpcError:
                        if (self._sub_stop.is_set()
                                or self._reconnect_window <= 0):
                            return
                        # Head restart: re-dial and resume from our
                        # cursor (the persisted event log keeps it
                        # valid).
                        from ray_tpu._private.retry import RetryPolicy
                        try:
                            # stop-interruptible backoff: close() must
                            # not wait out a multi-second redial sleep
                            new = RetryPolicy.default(
                                deadline_s=self._reconnect_window).run(
                                lambda: rpc.connect(self.addr,
                                                    timeout=None),
                                loop="head.subscribe_redial",
                                retry_on=(OSError,),
                                abort=self._sub_stop.is_set,
                                sleep=self._sub_stop.wait)
                        except OSError:
                            return
                        self._sub_swap(sub, new)
                        sub = new
                        continue
                    cursor = out["cursor"]
                    for event in out["events"]:
                        try:
                            callback(event)
                        except Exception:
                            pass
            finally:
                self._sub_swap(sub, None)
                sub.close()

        t = threading.Thread(target=loop, daemon=True,
                             name=f"head-sub-{channel}")
        t.start()
        self._sub_threads.append(t)

    def publish(self, channel: str, event: Any) -> None:
        # rides _call: with reconnect_window > 0 a publish survives a
        # head restart like every other head RPC (a direct client.call
        # here bypassed the redial path and failed mid-restart)
        self._call("publish", channel=channel, event=event)

    def stop_head(self) -> None:
        try:
            self._client.call("head_stop", timeout=2.0)
        except rpc.RpcError:
            pass

    def close(self) -> None:
        self._sub_stop.set()
        # closing the per-channel sub clients unblocks their parked
        # long-polls, so the threads exit instead of leaking sockets
        with self._sub_lock:
            subs = list(self._sub_clients)
        for sub in subs:
            sub.close()
        cur = threading.current_thread()
        for t in self._sub_threads:
            if t is not cur:        # close() from a callback thread
                t.join(timeout=2.0)
        self._client.close()


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--state-path", default="",
                        help="sqlite file for KV/pubsub persistence (FT)")
    parser.add_argument("--announce-fd", type=int, default=-1,
                        help="write the bound port here once listening")
    args = parser.parse_args()
    from ray_tpu._private import netchaos as _nc
    _nc.set_local_role("head")
    from ray_tpu._private import eventloop
    eventloop.set_proc_label("head")
    server = rpc.serve(HeadService(state_path=args.state_path or None),
                       host=args.host, port=args.port).start()
    try:    # continuous profiler (profiling_hz knob; default off)
        from ray_tpu.util import profiling as _profiling
        _profiling.maybe_start_from_config("head")
    except Exception:
        pass
    if args.announce_fd >= 0:
        import os

        os.write(args.announce_fd, f"{server.addr[1]}\n".encode())
        os.close(args.announce_fd)
    threading.Event().wait()  # serve forever


if __name__ == "__main__":
    main()
