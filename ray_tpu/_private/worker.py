"""The Runtime: task submission, execution, objects, actors, recovery.

This is the core-worker equivalent (reference ``src/ray/core_worker/``): it
owns task submission (``NormalTaskSubmitter`` / ``ActorTaskSubmitter``), the
dependency resolver, result storage (inline memory store for small values,
node object store for large ones), distributed refcounting hooks, task retries
and lineage-based object reconstruction (``task_manager.h``,
``object_recovery_manager.h``), and the actor lifecycle driven through GCS
state (``gcs_actor_manager.cc``).

Topology: one Runtime per driver process hosts N virtual nodes (the test
cluster fixture of the reference, ``python/ray/cluster_utils.py``, is the
*primary* deployment shape here for a single host; multi-host attaches via
the coordination service in later rounds).
"""

from __future__ import annotations

import inspect
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Set, Tuple

from ray_tpu import exceptions as exc
from ray_tpu._private import events as trace_events
from ray_tpu._private import runtime_context
from ray_tpu._private.gcs import GCS, ActorInfo, ActorState, NodeInfo
from ray_tpu._private.lock_sanitizer import tracked_lock
from ray_tpu._private.ids import (ActorID, JobID, NodeID, ObjectID, TaskID,
                                  WorkerID, next_seqno)
from ray_tpu._private.node import ActorExecutor, Node
from ray_tpu._private.object_ref import FutureTable, ObjectRef
from ray_tpu._private.object_store import LocalObjectStore, _nbytes_of
from ray_tpu._private.refcount import LineageTable, ReferenceCounter
from ray_tpu._private.scheduler import ClusterScheduler, SchedulingError
from ray_tpu._private.serialization import SerializationContext
from ray_tpu._private.task_spec import TaskKind, TaskSpec

# Values at or below this go to the owner's in-process memory store and
# survive node failures (reference: max_direct_call_object_size = 100 KiB,
# ray_config_def.h:195).
INLINE_OBJECT_SIZE = 100 * 1024

_global_runtime: Optional["Runtime"] = None
# tracked when the sanitizer env is set BEFORE import (module scope)
_global_lock = tracked_lock("worker.global_init", reentrant=False)


def global_runtime() -> Optional["Runtime"]:
    return _global_runtime


def global_worker() -> "Runtime":
    if _global_runtime is None:
        raise RuntimeError(
            "ray_tpu has not been initialized; call ray_tpu.init() first")
    return _global_runtime


class TaskState:
    PENDING_DEPS = "PENDING_ARGS_AVAIL"
    QUEUED = "PENDING_NODE_ASSIGNMENT"
    RUNNING = "RUNNING"
    FINISHED = "FINISHED"
    FAILED = "FAILED"
    CANCELLED = "CANCELLED"


class _InFlightTask:
    __slots__ = ("spec", "state", "node_id", "cancelled", "deps_remaining",
                 "lock")

    def __init__(self, spec: TaskSpec):
        self.spec = spec
        self.state = TaskState.PENDING_DEPS
        self.node_id: Optional[NodeID] = None
        self.cancelled = False
        self.deps_remaining = 0
        self.lock = threading.Lock()


class GeneratorState:
    """Producer/consumer state for a streaming-generator task.

    Reference: ``ReportGeneratorItemReturns`` proactive item reporting +
    ``GeneratorBackpressureWaiter`` (core_worker/generator_waiter.h).
    """

    def __init__(self, backpressure_num_objects: int = -1):
        self.cond = threading.Condition()
        self.items: List[ObjectRef] = []
        self.produced = 0
        self.consumed = 0
        self.finished = False
        self.error: Optional[BaseException] = None
        self.backpressure = backpressure_num_objects

    def report_item(self, ref: ObjectRef) -> None:
        with self.cond:
            self.items.append(ref)
            self.produced += 1
            self.cond.notify_all()
            if self.backpressure > 0:
                while (not self.finished
                       and self.produced - self.consumed >= self.backpressure):
                    self.cond.wait(1.0)

    def finish(self, error: Optional[BaseException] = None) -> None:
        with self.cond:
            self.finished = True
            self.error = error
            self.cond.notify_all()

    def next_ref(self, index: int, timeout: Optional[float] = None):
        deadline = None if timeout is None else time.monotonic() + timeout
        with self.cond:
            while True:
                if index < len(self.items):
                    ref = self.items[index]
                    self.consumed = max(self.consumed, index + 1)
                    self.cond.notify_all()
                    return ref
                if self.finished:
                    if self.error is not None:
                        raise self.error
                    raise StopIteration
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise exc.GetTimeoutError("generator item timeout")
                self.cond.wait(remaining)


class Runtime:
    def __init__(self, num_nodes: int = 1,
                 resources_per_node: Optional[Dict[str, float]] = None,
                 object_store_memory: int = 2 * 1024 ** 3,
                 namespace: Optional[str] = None,
                 session_dir: Optional[str] = None,
                 cluster: Optional[str] = None,
                 address: Optional[str] = None,
                 job_config=None):
        self.job_id = JobID.from_random()
        self.worker_id = WorkerID.from_random()
        self.namespace = namespace or self.job_id.hex()
        # per-job config (reference: JobConfig serialized at connect —
        # worker.py:2347): job-default runtime env consumed by
        # prepare_runtime_env. code_search_path rides that env as
        # py_modules — PRE-EXISTING pool workers (forked before this
        # init) never see driver sys.path edits, but py_modules
        # materialize per task in any worker; it also joins the
        # driver's own sys.path for local imports.
        self.job_config = job_config
        self._job_default_env = None
        if job_config is not None:
            import sys as _sys
            env = dict(job_config.runtime_env or {})
            if job_config.code_search_path:
                paths = [os.path.abspath(p)
                         for p in job_config.code_search_path]
                env["py_modules"] = (list(env.get("py_modules") or [])
                                     + paths)
                for p in paths:
                    if p not in _sys.path:
                        _sys.path.insert(0, p)
            self._job_default_env = env or None
        self.session_dir = session_dir or os.path.join(
            "/tmp", "ray_tpu", f"session_{self.job_id.hex()}")
        os.makedirs(self.session_dir, exist_ok=True)

        # Worker log capture + tail-to-driver (reference:
        # _private/log_monitor.py + worker.py:2164 print_worker_logs).
        # The log dir is process-stable (NOT per-session): pooled workers
        # outlive init/shutdown cycles and must keep a valid log target;
        # start_at_end skips any previous session's lines.
        self._log_monitor = None
        from ray_tpu._private import log_monitor as _lm
        if _lm.log_to_driver_enabled():
            self._log_monitor = _lm.LogMonitor(
                _lm.session_log_dir(), _lm.make_driver_printer(),
                start_at_end=True)

        self.gcs = GCS()
        self.scheduler = ClusterScheduler()
        # Multi-tenant fair share (ray_tpu/tenancy): submit-time
        # admission verdicts + deficit-ordered dispatch. The manager is
        # always constructed (job records and /api/jobs work either
        # way); enforcement only hooks into scheduler/node dispatch
        # when the `fairshare` flag is on, so the single-tenant hot
        # path stays untouched.
        from ray_tpu.tenancy import TenancyManager
        self.tenancy = TenancyManager(runtime=self)
        if self.tenancy.enabled:
            self.scheduler.tenancy = self.tenancy
        self.futures = FutureTable()
        self.lineage = LineageTable()
        self.refcounter = ReferenceCounter(on_zero=self._free_object)
        self.serialization = SerializationContext()

        # Owner memory store: inline values + error objects; survives node
        # death (reference: CoreWorkerMemoryStore).
        self.memory_store = LocalObjectStore(
            NodeID.nil(), capacity_bytes=1 << 62)

        self._nodes: Dict[NodeID, Node] = {}  #: guarded by self._nodes_lock
        self._nodes_lock = tracked_lock("worker.nodes")    # reentrant
        #: guarded by self._loc_lock
        self._locations: Dict[ObjectID, Set[NodeID]] = {}
        self._loc_lock = tracked_lock("worker.locations", reentrant=False)
        # Objects whose every copy died with a node; reconstruction is
        # triggered lazily on the next get/wait/dependency touch.
        self._lost: Set[ObjectID] = set()
        # Proactive dep-push staging (objectplane): a small shared pool
        # (never thread-per-enqueue) + an in-flight (dep, dest) table so
        # one fan-out stages each dep once.
        from ray_tpu._private.thread_pool import DaemonThreadPool
        self._prefetch_pool = DaemonThreadPool(2, name="push-prefetch")
        #: guarded by self._prefetch_lock
        self._prefetch_inflight: Set[tuple] = set()
        self._prefetch_lock = tracked_lock("worker.push_prefetch",
                                           reentrant=False)

        self._tasks: Dict[TaskID, _InFlightTask] = {}  #: guarded by self._tasks_lock
        self._tasks_lock = tracked_lock("worker.tasks", reentrant=False)

        #: guarded by self._actor_lock
        self._actor_pending_tasks: Dict[ActorID, List[TaskSpec]] = {}
        self._actor_lock = tracked_lock("worker.actors")   # reentrant
        self._actor_executors: Dict[ActorID, ActorExecutor] = {}
        # actor_id -> DaemonHandle for actors hosted on node daemons
        self._remote_actors: Dict[ActorID, Any] = {}

        self._generators: Dict[TaskID, GeneratorState] = {}

        # ICI-topology-aware gang scheduling: when a slice topology is
        # declared, TPU placement-group bundles claim contiguous
        # sub-slices instead of landing by resource count
        # (bundle_scheduling_policy.h role; SURVEY §2.3 gang row).
        from ray_tpu._private.config import cfg as _cfg
        # deterministic fault injection: the `failpoints` flag activates
        # the registry for this process (spawned daemons/heads/workers
        # activate from the inherited RAY_TPU_FAILPOINTS env var)
        from ray_tpu._private import failpoints as _failpoints
        _failpoints.maybe_activate_from_config(_cfg())
        # network chaos rides the same activation discipline: the
        # `net_chaos` flag arms this process's link policies and
        # exports RAY_TPU_NET_CHAOS for spawned processes
        from ray_tpu._private import netchaos as _netchaos
        _netchaos.maybe_activate_from_config(_cfg())
        _netchaos.set_local_role("driver")
        from ray_tpu._private import eventloop as _eventloop
        _eventloop.set_proc_label("driver")
        self.tpu_topology = None
        _topo_spec = _cfg().tpu_topology
        if _topo_spec:
            from ray_tpu.parallel.topology import TpuTopologyManager
            self.tpu_topology = TpuTopologyManager.from_spec(_topo_spec)

        from ray_tpu.util.placement_group import PlacementGroupManager
        self.pg_manager = PlacementGroupManager(self)
        self._shutdown = False
        self.stats = {"tasks_submitted": 0, "tasks_finished": 0,
                      "tasks_retried": 0, "objects_reconstructed": 0,
                      "actor_restarts": 0,
                      # graceful-drain counters (surfaced on /metrics as
                      # ray_tpu_drains_total etc. by prometheus_text)
                      "drains_total": 0, "drain_objects_migrated": 0,
                      "drain_actors_migrated": 0,
                      "drain_escalations_total": 0}
        from ray_tpu._private.events import TaskEventBuffer
        self.task_events = TaskEventBuffer()
        # continuous profiler (profiling_hz knob, default off): the
        # driver lane of `ray-tpu profile` / util.state.cluster_profile
        from ray_tpu.util import profiling as _profiling
        _profiling.maybe_start_from_config("driver")

        # Process workers: the default execution path for host-plane
        # tasks/actors (VERDICT r1 #2). Accelerator-plane work (TPU
        # resources / device-tier args) stays in this process — it owns
        # the mesh.
        from ray_tpu._private.worker_process import ProcessRouter
        self.process_router = ProcessRouter(self)

        # OOM defense: sample driver+worker RSS, kill a worker per policy
        # on threshold breach (reference: common/memory_monitor.h:52 +
        # raylet/worker_killing_policy*.h). The driver (mesh owner) is
        # never a victim.
        from ray_tpu._private.memory_monitor import MemoryMonitor
        self.memory_monitor = MemoryMonitor(self)
        from ray_tpu._private.config import cfg
        if cfg().memory_monitor:
            self.memory_monitor.start()

        if resources_per_node is None:
            resources_per_node = self._detect_resources()
        self.cluster_backend = None
        if cluster is None:
            cluster = cfg().cluster or None
        if address:
            # Join an EXISTING `ray-tpu start` cluster as a new driver
            # (reference: ray.init(address=...) against a running GCS).
            from ray_tpu._private.cluster import ClusterBackend
            backend = ClusterBackend.attach(self, address)
            self.cluster_backend = backend
            for node_id, handle in backend.daemons.items():
                self.add_remote_node(
                    handle, dict(backend.node_resources[node_id]))
        elif cluster == "daemons":
            # Real head + node-daemon OS processes behind the wire
            # protocol; every schedulable node is a daemon. In-process /
            # accelerator work still executes driver-side, on the
            # assigned node's dispatch thread (see _execute_inline).
            from ray_tpu._private.cluster import ClusterBackend
            backend = ClusterBackend(self, num_nodes,
                                     dict(resources_per_node),
                                     object_store_bytes=object_store_memory)
            self.cluster_backend = backend
            for node_id, handle in backend.daemons.items():
                self.add_remote_node(handle, dict(resources_per_node))
        else:
            for _ in range(num_nodes):
                self.add_node(dict(resources_per_node),
                              object_store_memory=object_store_memory)
        if self.cluster_backend is not None and self.tenancy.enabled:
            # adopt quota records persisted at the head (other drivers
            # or a previous incarnation of this one may have set them)
            self.tenancy.load_from_head(self.cluster_backend.head)

    # ------------------------------------------------------------------
    # cluster topology
    # ------------------------------------------------------------------
    @staticmethod
    def _detect_resources() -> Dict[str, float]:
        res: Dict[str, float] = {"CPU": float(os.cpu_count() or 1)}
        try:
            import jax
            chips = [d for d in jax.devices() if d.platform != "cpu"]
            if chips:
                res["TPU"] = float(len(chips))
        except Exception:
            pass
        return res

    def add_node(self, resources: Dict[str, float],
                 labels: Optional[Dict[str, str]] = None,
                 object_store_memory: int = 2 * 1024 ** 3) -> Node:
        node_id = NodeID.from_random()
        store = LocalObjectStore(
            node_id, object_store_memory,
            spill_dir=os.path.join(self.session_dir, "spill",
                                   node_id.hex()[:8]))
        node = Node(node_id, resources, labels or {}, store,
                    execute_task=self._execute_on_node)
        if self.tenancy.enabled:
            node.tenancy = self.tenancy
        with self._nodes_lock:
            self._nodes[node_id] = node
        self.gcs.register_node(node.info())
        from ray_tpu._private.scheduler import bump_cluster_epoch
        bump_cluster_epoch()
        return node

    def add_remote_node(self, handle, resources: Dict[str, float]) -> Node:
        """Register a node daemon process as a schedulable node. The Node
        machinery (ledger, dispatch queue, backlog) runs driver-side —
        single-controller placement — while execution, workers, and the
        object payloads live in the daemon."""
        from ray_tpu._private.cluster import RemoteStore
        store = RemoteStore(handle)
        node = Node(handle.node_id, resources, {}, store,
                    execute_task=self._execute_on_remote_node)
        if self.tenancy.enabled:
            node.tenancy = self.tenancy
        node.daemon = handle
        handle.runtime = self   # node_pressure pushes resolve the Node
        # proactive dep staging: enqueue-time pushes overlap the
        # transfer with the task's queue wait (PushManager dedupes)
        node.prefetch = (lambda spec, _node=node:
                         self._push_prefetch_deps(spec, _node))
        with self._nodes_lock:
            self._nodes[handle.node_id] = node
        self.gcs.register_node(node.info())
        from ray_tpu._private.scheduler import bump_cluster_epoch
        bump_cluster_epoch()
        return node

    def _push_prefetch_deps(self, spec: TaskSpec, node: Node) -> None:
        """Proactively push task deps that live only on OTHER daemon
        nodes to ``node`` (reference: ``object_manager.cc:354 Push``) —
        by the time the task (or a same-node consumer) needs them, a
        local copy exists. The PushManager dedupes in-flight pushes,
        copies the destination already holds, and chunks a concurrent
        pull already transferred; failures are harmless (the classic
        pull/owner path still serves the object on demand)."""
        deps = spec.dependencies()
        if not deps or getattr(node, "daemon", None) is None:
            return
        from ray_tpu._private.config import cfg
        if not cfg().push_prefetch:
            return
        if getattr(node, "pressure_level", "ok") != "ok":
            # soft/hard memory pressure: stop staging optional copies
            # onto the node — the demand pull path still serves the
            # task's args when it actually runs (pressure.py)
            return
        with self._loc_lock:
            locs = {dep: list(self._locations.get(dep, ()))
                    for dep in deps}
        work = []
        for dep, node_ids in locs.items():
            if not node_ids or node.node_id in node_ids:
                continue
            src = self.get_node(node_ids[0])
            src_daemon = getattr(src, "daemon", None)
            meta_of = getattr(getattr(src, "store", None), "meta_of",
                              None)
            if (src is None or not src.alive or src_daemon is None
                    or meta_of is None or node.store.contains(dep)):
                continue
            # driver-side (dep, dest) in-flight dedupe: a fan-out of
            # tasks sharing one dep must stage it ONCE, not once per
            # enqueue (the daemon's PushManager dedupes too, but this
            # avoids the redundant RPCs entirely)
            fly = (dep, node.node_id)
            with self._prefetch_lock:
                if fly in self._prefetch_inflight:
                    continue
                self._prefetch_inflight.add(fly)
            try:
                key, nbytes, raw = meta_of(dep)
            except KeyError:
                with self._prefetch_lock:
                    self._prefetch_inflight.discard(fly)
                continue
            work.append((dep, fly, src_daemon, key, nbytes, raw))
        if not work:
            return

        def run_one(dep, fly, src_daemon, key, nbytes, raw) -> None:
            try:
                out = src_daemon.push_object(
                    key, node.daemon.addr, ref=dep.binary())
                if out.get("ok"):
                    node.store.register_remote(dep, key, nbytes,
                                               raw=raw)
                    with self._loc_lock:
                        self._locations.setdefault(dep, set()).add(
                            node.node_id)
                    self.stats["objects_push_prefetched"] = (
                        self.stats.get("objects_push_prefetched", 0)
                        + 1)
            except Exception:
                pass            # on-demand pull/owner path covers it
            finally:
                with self._prefetch_lock:
                    self._prefetch_inflight.discard(fly)

        # small shared pool, never thread-per-task: a 10k-task fan-out
        # with remote deps must not spawn 10k threads each parked in a
        # (bounded) push RPC
        for item in work:
            self._prefetch_pool.submit(lambda it=item: run_one(*it))

    def _execute_on_remote_node(self, spec: TaskSpec, node: Node) -> None:
        """Task execution on a node-daemon process (wire protocol:
        RequestWorkerLease + PushTask; reference call stack SURVEY §3.1).
        """
        from ray_tpu._private.cluster import DaemonCrashed
        if spec.kind == TaskKind.ACTOR_CREATION:
            self._execute_actor_creation(spec, node)
            return
        if spec.kind == TaskKind.ACTOR_TASK:
            self._run_actor_task_from_node(spec, node)
            return
        with self._tasks_lock:
            inflight = self._tasks.get(spec.task_id)
        if inflight is not None:
            with inflight.lock:
                if inflight.cancelled:
                    return
                inflight.state = TaskState.RUNNING
        # same RUNNING transition the in-process path records: the
        # timeline/chrome-trace pairs RUNNING with FINISHED/FAILED
        self.task_events.record(
            task_id=spec.task_id.hex(), name=spec.name, event="RUNNING",
            node_id=node.node_id.hex())
        try:
            args, kwargs = self._resolve_args(spec)
        except exc.TaskError as te:
            self._finish_task(spec, node, error=te)
            return
        from ray_tpu._private.cluster import RemoteWorkerCrashed
        from ray_tpu._private.worker_process import _wants_accelerator
        demand = getattr(spec, "pg_demand", None) or spec.resources
        payload = None
        if not getattr(spec, "in_process", False) and \
                not _wants_accelerator(demand):
            payload = self.process_router._serialize_payload(spec, args,
                                                             kwargs)
        if payload is None:
            # Accelerator-plane / in_process / unserializable work stays
            # in the mesh-owning driver process: run it right here on the
            # node's dispatch thread (resources stay accounted on this
            # node; the compute itself is driver-side XLA).
            self._execute_inline(spec, node, args, kwargs)
            return
        fid, args_blob = payload
        from ray_tpu.util import tracing
        try:
            with tracing.span(f"task::{spec.name}",
                              task_id=spec.task_id.hex()[:16]):
                kind, value = node.daemon.execute_task(spec, fid,
                                                       args_blob)
        except RemoteWorkerCrashed as crash:
            # one worker died; the daemon (node) is fine — plain retry
            self._on_process_task_crash(spec, node, crash)
            return
        except DaemonCrashed as crash:
            self._on_daemon_crash(node)
            self._on_process_task_crash(spec, node, crash)
            return
        self._finish_remote_outcome(spec, node, kind, value)

    def _finish_remote_outcome(self, spec: TaskSpec, node: Node,
                               kind: str, value) -> None:
        if kind == "err":
            with self._tasks_lock:
                inflight = self._tasks.get(spec.task_id)
            if (inflight is not None and inflight.cancelled
                    and isinstance(value, KeyboardInterrupt)):
                self._release_task_resources(spec, node)
                self._fail_task(spec, exc.TaskError(
                    exc.TaskCancelledError(spec.task_id), spec.name))
                return
            self._finish_task(spec, node, error=exc.TaskError(
                value, spec.name))
            return
        if kind == "gen" or spec.num_returns in ("streaming", "dynamic"):
            self._drain_generator(spec, node, value)
            return
        if kind == "stored":
            daemon_key, nbytes = value
            n = spec.num_returns
            if n == 1 or not isinstance(n, int):
                t_result = (time.perf_counter()
                            if getattr(spec, "trace_sampled", False)
                            else 0.0)
                oid = spec.return_ids[0]
                node.store.register_remote(oid, daemon_key, nbytes)
                with self._loc_lock:
                    self._locations.setdefault(oid, set()).add(
                        node.node_id)
                self.task_events.record(task_id=spec.task_id.hex(),
                                        name=spec.name, event="FINISHED")
                self._release_task_resources(spec, node)
                self.futures.complete(oid)
                if t_result:
                    now = time.perf_counter()
                    trace_events.record_phase_rt(
                        spec, "result", now - t_result,
                        node.node_id.hex(),
                        start_wall=trace_events.wall_at(t_result),
                        end_mono=now)
                self._on_task_done(spec, TaskState.FINISHED)
                return
            # multi-return tuple stored remotely: fetch once and split
            value = node.store.daemon.get_object_blob(daemon_key)
            import cloudpickle as _cp
            value = _cp.loads(value)
            kind = "ok"
        self._finish_task(spec, node, result=value)

    def _on_daemon_crash(self, node: Node) -> None:
        """Daemon RPC failure observed first-hand: report to the head and
        run the node-death flow (objects lost, actors restart)."""
        backend = self.cluster_backend
        handle = getattr(node, "daemon", None)
        if backend is None or handle is None:
            return
        backend.report_daemon_dead(handle, "rpc failure")
        if self.get_node(node.node_id) is not None:
            try:
                self.remove_node(node, _from_cluster=True)
            except Exception:
                pass

    def _run_actor_task_from_node(self, spec: TaskSpec, node: Node) -> None:
        # Actor tasks are driven by the ActorExecutor, not the dispatch
        # queue; reaching here means a retry raced — resubmit properly.
        inflight = None
        with self._tasks_lock:
            inflight = self._tasks.get(spec.task_id)
        self._submit_actor_task(spec, inflight, spec.dependencies())

    def remove_node(self, node: Node, _from_cluster: bool = False) -> None:
        """Simulate node failure: lose its objects, tasks, and actors.
        For daemon-backed nodes this hard-kills the daemon process."""
        from ray_tpu._private.scheduler import bump_cluster_epoch
        bump_cluster_epoch()    # before the pop: no stale cache window
        with self._nodes_lock:
            present = self._nodes.pop(node.node_id, None) is not None
        if not present:
            # already removed — a clean drain completion and the head's
            # death event (or deadline escalation) race here; the death
            # flow must run exactly once
            return
        handle = getattr(node, "daemon", None)
        if handle is not None and not _from_cluster:
            handle.sigkill()
            if self.cluster_backend is not None:
                try:
                    self.cluster_backend.head.mark_node_dead(
                        node.node_id.hex(), "removed")
                except Exception:
                    pass
        pending_by_actor = node.shutdown()
        self.gcs.mark_node_dead(node.node_id)
        # Objects on this node are lost.
        lost = node.store.object_ids()
        with self._loc_lock:
            for oid in lost:
                locs = self._locations.get(oid)
                if locs is not None:
                    locs.discard(node.node_id)
                    if not locs:
                        del self._locations[oid]
                        self.futures.reset(oid)
                        self._lost.add(oid)
        node.store.close()
        self.pg_manager.on_node_death(node.node_id)
        # Actors on this node die (and may restart).
        for actor_id, pending in pending_by_actor.items():
            self._handle_actor_death(actor_id, "node died",
                                     pending_tasks=pending,
                                     may_restart=True)

    def nodes(self) -> List[Node]:
        with self._nodes_lock:
            return list(self._nodes.values())

    def alive_nodes(self) -> List[Node]:
        return [n for n in self.nodes() if n.alive]

    def schedulable_nodes(self) -> List[Node]:
        """Alive nodes accepting NEW placements (draining excluded);
        falls back to every alive node when all are draining."""
        alive = self.alive_nodes()
        return [n for n in alive
                if not getattr(n, "draining", False)] or alive

    def get_node(self, node_id: NodeID) -> Optional[Node]:
        with self._nodes_lock:
            return self._nodes.get(node_id)

    def head_node(self) -> Node:
        nodes = self.alive_nodes()
        if not nodes:
            raise RuntimeError("cluster has no alive nodes")
        return nodes[0]

    def cluster_resources(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for n in self.alive_nodes():
            for k, v in n.ledger.total.items():
                out[k] = out.get(k, 0.0) + v
        return out

    def available_resources(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for n in self.alive_nodes():
            for k, v in n.ledger.available().items():
                out[k] = out.get(k, 0.0) + v
        return out

    # ------------------------------------------------------------------
    # graceful node drain (preemption / downscale / maintenance)
    # ------------------------------------------------------------------
    def drain_node(self, node, deadline_s: Optional[float] = None,
                   reason: str = "preemption") -> bool:
        """Gracefully drain a node: no new placements land on it, its
        queued tasks resubmit elsewhere, primary object replicas and
        actors migrate off it proactively, and when its in-flight work
        completes it leaves the cluster cleanly. If the deadline expires
        first, the drain escalates into the ordinary node-death path
        (lineage reconstruction covers anything unmigrated).

        ``node`` is a Node, NodeID, or node-id hex string. Returns True
        if a drain was started (False: unknown/dead/already draining).
        """
        if not isinstance(node, Node):
            node_id = (NodeID.from_hex(node) if isinstance(node, str)
                       else node)
            node = self.get_node(node_id)
            if node is None:
                return False
        if deadline_s is None:
            from ray_tpu._private.config import cfg
            deadline_s = cfg().drain_deadline_s
        backend = self.cluster_backend
        if backend is not None and getattr(node, "daemon", None) is not None:
            # Publish through the head so the DRAINING membership state
            # (and its deadline escalation) outlives this driver — and
            # survives a head restart via the persisted drain record.
            try:
                backend.head.drain_node(node.node_id.hex(), deadline_s,
                                        reason)
            except Exception:
                pass        # head unreachable: drain locally anyway
        started = self.begin_node_drain(node, deadline_s, reason)
        # the head's own node_drain event may have won the race to start
        # the local migration — that still counts as "draining now"
        return started or bool(getattr(node, "draining", False))

    def begin_node_drain(self, node: Node, deadline_s: float,
                         reason: str) -> bool:
        """Idempotent driver-side entry (also fed by the head's
        ``node_drain`` pubsub event): flips the node to DRAINING and
        starts the migration worker."""
        with self._nodes_lock:
            if (not node.alive or getattr(node, "draining", False)
                    or self._nodes.get(node.node_id) is not node):
                return False
            node.start_drain()
        self.stats["drains_total"] += 1
        threading.Thread(
            target=self._drain_node_worker,
            args=(node, deadline_s, reason), daemon=True,
            name=f"drain-{node.node_id.hex()[:8]}").start()
        return True

    def _drain_node_worker(self, node: Node, deadline_s: float,
                           reason: str) -> None:
        deadline = time.monotonic() + max(0.0, deadline_s)
        # flush coalesced frees first: the draining daemon's store should
        # not migrate (or hold) objects the driver already released
        for handle in ([getattr(node, "daemon", None)]
                       + [getattr(n, "daemon", None)
                          for n in self.alive_nodes()]):
            if handle is not None:
                try:
                    handle.flush_frees()
                except Exception:
                    pass
        try:
            self._migrate_node_objects(node)
            self._migrate_node_actors(node, reason, deadline=deadline)
        except Exception:
            pass    # escalation still bounds the drain; lineage recovers
        while time.monotonic() < deadline:
            with node._running_lock:
                busy = bool(node._running)
            if not busy and node._backlog_n == 0 and node._queue.empty():
                # Clean drain: sweep again — results stored (and actors
                # created) WHILE draining live on this node too — then
                # leave the cluster with zero reconstruction debt.
                try:
                    self._migrate_node_actors(node, reason,
                                              deadline=deadline)
                    self._migrate_node_objects(node)
                except Exception:
                    pass
                self._finish_drain(node, reason)
                return
            time.sleep(0.05)
        self._escalate_drain(node, reason)

    def _migrate_node_objects(self, node: Node) -> int:
        """Copy primary (sole-replica) objects off the draining node so
        the eventual departure loses nothing (``objects_reconstructed``
        stays 0 when migration wins the race against the deadline)."""
        targets = [n for n in self.alive_nodes()
                   if n.node_id != node.node_id
                   and not getattr(n, "draining", False)]
        if not targets:
            return 0
        from ray_tpu._private import failpoints as _fp
        moved = 0
        i = 0
        for oid in node.store.object_ids():
            with self._loc_lock:
                locs = self._locations.get(oid, set())
                if locs - {node.node_id}:
                    continue        # a replica already lives elsewhere
            target = targets[i % len(targets)]
            i += 1
            if _fp.ENABLED:
                try:
                    _fp.fire("drain.migrate_object", oid=oid.hex())
                except Exception:
                    continue    # this object stays; lineage covers it
            try:
                src_daemon = getattr(node, "daemon", None)
                dst_daemon = getattr(target, "daemon", None)
                if src_daemon is not None and dst_daemon is not None:
                    # daemon→daemon transfer: bytes move directly over
                    # the object plane, never through the driver —
                    # proactive push first (chunked/deduped
                    # PushManager), pull as the fallback direction
                    key, nbytes, raw = node.store.meta_of(oid)
                    moved_ok = False
                    try:
                        moved_ok = src_daemon.push_object(
                            key, dst_daemon.addr,
                            ref=oid.binary()).get("ok", False)
                    except Exception:
                        moved_ok = False
                    if not moved_ok and not dst_daemon.pull_object(
                            key, from_addr=src_daemon.addr, priority=1):
                        continue
                    target.store.register_remote(oid, key, nbytes,
                                                 raw=raw)
                else:
                    value = node.store.get(oid)
                    # reuse the size cached at insert time — migrating
                    # a large pytree must not pay a fresh deep walk
                    target.store.put(oid, value,
                                     nbytes=node.store.nbytes_of(oid)
                                     or _nbytes_of(value))
            except Exception:
                continue
            with self._loc_lock:
                self._locations.setdefault(oid, set()).add(
                    target.node_id)
            moved += 1
        if moved:
            self.stats["drain_objects_migrated"] += moved
        return moved

    def _migrate_node_actors(self, node: Node, reason: str,
                             deadline: Optional[float] = None) -> int:
        """Restart the draining node's actors on surviving nodes via the
        existing restart machinery — graceful, so pending tasks replay
        on the new incarnation instead of failing, and the planned move
        does not consume the actors' max_restarts budget."""
        from ray_tpu._private.task_spec import (
            NodeAffinitySchedulingStrategy)
        with node._actors_lock:
            actors = dict(node.actors)
        migrate: Dict[ActorID, ActorExecutor] = {}
        for actor_id, executor in actors.items():
            info = self.gcs.get_actor_info(actor_id)
            strat = getattr(getattr(info, "creation_spec", None),
                            "scheduling_strategy", None)
            if (isinstance(strat, NodeAffinitySchedulingStrategy)
                    and not strat.soft
                    and strat.node_id == node.node_id.hex()):
                # hard-pinned HERE: it cannot live anywhere else —
                # leave it to finish work until the node departs
                continue
            migrate[actor_id] = executor
        with node._actors_lock:
            for actor_id in migrate:
                node.actors.pop(actor_id, None)
        moved = 0
        cause = f"node draining ({reason})"
        for actor_id, executor in migrate.items():
            pending = executor.kill(cause)
            # Let an IN-FLIGHT method finish before the actor's worker
            # process is recycled: kill() stops dispatch, so the
            # executor threads exit right after the current call — a
            # planned move should not crash a running call. Bounded by
            # the drain deadline (a stuck call escalates instead).
            for t in executor._threads:
                budget = 1.0
                if deadline is not None:
                    budget = min(budget, max(
                        0.0, deadline - time.monotonic()))
                t.join(timeout=budget)
            try:
                self._handle_actor_death(actor_id, cause,
                                         pending_tasks=pending,
                                         may_restart=True, graceful=True)
                moved += 1
            except Exception:
                continue
        if moved:
            self.stats["drain_actors_migrated"] += moved
        return moved

    def _finish_drain(self, node: Node, reason: str) -> None:
        """Clean completion: the node leaves via the normal removal flow,
        but with its objects replicated and actors already elsewhere."""
        if self.get_node(node.node_id) is None:
            return      # a death event won the race
        backend = self.cluster_backend
        handle = getattr(node, "daemon", None)
        if backend is not None and handle is not None:
            try:
                backend.head.mark_node_dead(node.node_id.hex(),
                                            f"drained ({reason})")
            except Exception:
                pass
            try:
                handle.stop()
            except Exception:
                pass
        try:
            self.remove_node(node, _from_cluster=True)
        except Exception:
            pass

    def count_drain_escalation(self, node: Node) -> None:
        """Exactly-once escalation accounting: the driver's own deadline
        timer and the head's death event race to escalate the same
        drain — whichever wins counts, the loser is a no-op."""
        with self._nodes_lock:
            if getattr(node, "_drain_escalated", False):
                return
            node._drain_escalated = True
        self.stats["drain_escalations_total"] += 1

    def _escalate_drain(self, node: Node, reason: str) -> None:
        """Deadline expired with work still on the node: fall back to
        the ordinary node-death path (hard kill; retries + lineage
        reconstruction recover whatever did not migrate in time)."""
        if self.get_node(node.node_id) is None:
            return      # drained cleanly / head escalated first
        self.count_drain_escalation(node)
        from ray_tpu._private import failpoints as _fp
        if _fp.ENABLED:
            try:
                # delay arm stretches the escalation window; an error
                # arm must NOT suppress the escalation (the node would
                # linger draining forever)
                _fp.fire("drain.deadline", node=node.node_id.hex())
            except Exception:
                pass
        try:
            self.remove_node(node)
        except Exception:
            pass

    def on_node_task_drained(self, spec: TaskSpec, node: Node) -> None:
        """A queued-but-unstarted task handed back by a draining node:
        reschedule it elsewhere WITHOUT consuming a retry (planned
        departure, not a failure)."""
        with self._tasks_lock:
            inflight = self._tasks.get(spec.task_id)
        if inflight is None:
            return
        with inflight.lock:
            if inflight.cancelled:
                return
        # one bounce only: if the scheduler sends it back (nothing else
        # fits), the draining node's dispatch loop runs it locally
        spec._drain_bounced = True
        self._schedule(spec, inflight)

    # ------------------------------------------------------------------
    # objects
    # ------------------------------------------------------------------
    def put(self, value: Any, _owner_pin: bool = False) -> ObjectRef:
        if isinstance(value, ObjectRef):
            raise TypeError("put() of an ObjectRef is not allowed "
                            "(pass the ref itself instead)")
        oid = ObjectID.from_random()
        ref = ObjectRef(oid, owner_hex=self.worker_id.hex(), task_name="put")
        self._store_value(oid, value)
        self.futures.complete(oid)
        if _owner_pin:
            self.refcounter.pin(oid)
        return ref

    def put_stored(self, oid_bin: bytes, key: bytes, nbytes: int,
                   raw, node_hex: str) -> ObjectRef:
        """Owner-side registration of a worker DIRECT put: the payload
        is already written + sealed in ``node``'s arena under ``key``
        (zero-copy object plane) — record ownership, location, and the
        raw-tier dtype/shape; no value ever reaches the driver."""
        oid = ObjectID(bytes(oid_bin))
        node = self.get_node(NodeID.from_hex(node_hex))
        store = getattr(node, "store", None) if node is not None else None
        register = getattr(store, "register_remote", None)
        if node is None or not node.alive or register is None:
            # unknown/dead/non-daemon node: the worker falls back to
            # the classic value put (its arena entry is aborted)
            raise RuntimeError(f"no daemon store on node {node_hex!r}")
        register(oid, bytes(key), int(nbytes),
                 raw=tuple(raw) if raw else None)
        if self.tenancy.enabled:
            from ray_tpu.tenancy import current_job_id
            jid = current_job_id(self)
            self.tenancy.note_put(
                oid.hex(), jid.hex() if jid is not None else "",
                int(nbytes))
        with self._loc_lock:
            self._locations.setdefault(oid, set()).add(node.node_id)
        ref = ObjectRef(oid, owner_hex=self.worker_id.hex(),
                        task_name="put")
        self.futures.complete(oid)
        return ref

    def _store_value(self, oid: ObjectID, value: Any,
                     prefer_node: Optional[Node] = None) -> None:
        nested = _find_nested_refs(value)
        if nested:
            self.refcounter.add_nested_refs(oid, [r.id for r in nested])
        size = _nbytes_of(value)
        if self.tenancy.enabled:
            from ray_tpu.tenancy import current_job_id
            jid = current_job_id(self)
            self.tenancy.note_put(
                oid.hex(), jid.hex() if jid is not None else "", size)
        if size <= INLINE_OBJECT_SIZE or prefer_node is None:
            self.memory_store.put(oid, value, nbytes=size)
            return
        prefer_node.store.put(oid, value, nbytes=size)
        with self._loc_lock:
            self._locations.setdefault(oid, set()).add(prefer_node.node_id)

    def _free_object(self, oid: ObjectID) -> None:
        """Refcount hit zero: drop the value everywhere + its lineage."""
        if self.tenancy.enabled:
            self.tenancy.note_free(oid.hex())
        self.memory_store.delete(oid)
        with self._loc_lock:
            locs = self._locations.pop(oid, set())
        for node_id in locs:
            node = self.get_node(node_id)
            if node is not None:
                node.store.delete(oid)
        self.lineage.release(oid)

    def _fetch_value(self, oid: ObjectID) -> Tuple[bool, Any]:
        """Return (found, value) looking across memory store + node stores."""
        if self.memory_store.contains(oid):
            return True, self.memory_store.get(oid)
        with self._loc_lock:
            locs = list(self._locations.get(oid, ()))
        for node_id in locs:
            node = self.get_node(node_id)
            if node is not None and node.alive and node.store.contains(oid):
                return True, node.store.get(oid)
        return False, None

    def _ensure_available(self, oid: ObjectID) -> None:
        """Kick off lineage reconstruction if every copy of oid was lost."""
        with self._loc_lock:
            was_lost = oid in self._lost
            self._lost.discard(oid)
        if was_lost:
            self._recover_object(
                ObjectRef(oid, _register=False))

    def get(self, refs: Sequence[ObjectRef],
            timeout: Optional[float] = None) -> List[Any]:
        deadline = None if timeout is None else time.monotonic() + timeout
        out: List[Any] = []
        for ref in refs:
            self._ensure_available(ref.id)
            remaining = None
            if deadline is not None:
                remaining = max(deadline - time.monotonic(), 0.0)
            if not self.futures.wait_for(ref.id, remaining):
                raise exc.GetTimeoutError(
                    f"get() timed out waiting for {ref}")
            value = self._get_one(ref, deadline)
            if isinstance(value, exc.TaskError):
                raise value.as_instanceof_cause()
            if isinstance(value, exc.RayTpuError):
                raise value
            out.append(value)
        return out

    def _get_one(self, ref: ObjectRef, deadline: Optional[float],
                 _depth: int = 0) -> Any:
        self._ensure_available(ref.id)
        found, value = self._fetch_value(ref.id)
        if found:
            return value
        # Object lost (node death). Attempt lineage reconstruction.
        if _depth > 100:
            raise exc.ObjectReconstructionFailedError(
                ref.id, "reconstruction recursion limit hit")
        self._recover_object(ref)
        remaining = None
        if deadline is not None:
            remaining = max(deadline - time.monotonic(), 0.0)
        if not self.futures.wait_for(ref.id, remaining):
            raise exc.GetTimeoutError(
                f"get() timed out waiting for reconstruction of {ref}")
        return self._get_one(ref, deadline, _depth + 1)

    def _recover_object(self, ref: ObjectRef) -> None:
        """Resubmit the producing task of a lost object (lineage recovery)."""
        spec = self.lineage.producer_of(ref.id)
        if spec is None:
            err = exc.ObjectLostError(
                ref.id, f"object {ref.id.hex()[:12]} was lost and has no "
                        f"lineage to reconstruct it (e.g. created by put())")
            self._store_value(ref.id, err)
            self.futures.complete(ref.id)
            return
        with self._tasks_lock:
            inflight = self._tasks.get(spec.task_id)
            if inflight is not None and inflight.state in (
                    TaskState.PENDING_DEPS, TaskState.QUEUED,
                    TaskState.RUNNING):
                return  # already being recomputed
        self.stats["objects_reconstructed"] += 1
        respec = _clone_spec_for_retry(spec)
        for oid in respec.return_ids:
            self.futures.reset(oid)
        self.submit_task(respec, record_lineage=False)

    def wait(self, refs: Sequence[ObjectRef], num_returns: int = 1,
             timeout: Optional[float] = None,
             fetch_local: bool = True) -> Tuple[List[ObjectRef], List[ObjectRef]]:
        if num_returns > len(refs):
            raise ValueError("num_returns exceeds the number of refs")
        for r in refs:
            self._ensure_available(r.id)
        ids = [r.id for r in refs]
        # Cap at num_returns even if more completed (API contract parity).
        done_list = self.futures.wait_any(ids, num_returns, timeout)
        done_ids = set(done_list[:num_returns])
        ready = [r for r in refs if r.id in done_ids]
        not_ready = [r for r in refs if r.id not in done_ids]
        return ready, not_ready

    # ------------------------------------------------------------------
    # task submission
    # ------------------------------------------------------------------
    def submit_task(self, spec: TaskSpec,
                    record_lineage: bool = True) -> List[ObjectRef]:
        if self.tenancy.enabled:
            # fair-share admission verdict; REJECTED raises
            # AdmissionRejectedError here, before any future/lineage
            # state exists (the backpressure contract)
            self.tenancy.admit(spec)
        self.stats["tasks_submitted"] += 1
        trace_events.stamp_trace(spec)
        refs = [ObjectRef(oid, owner_hex=self.worker_id.hex(),
                          task_name=spec.name) for oid in spec.return_ids]
        for oid in spec.return_ids:
            self.futures.register(oid)
        deps = spec.dependencies()
        if deps:
            self.refcounter.add_submitted_task_refs(deps)
        if record_lineage and spec.max_retries != 0:
            self.lineage.record(spec.return_ids, spec)
        if spec.num_returns in ("streaming", "dynamic"):
            # Pre-create the generator state so the configured backpressure
            # applies even if the consumer races the producer to it.
            self._generators.setdefault(
                spec.task_id, GeneratorState(spec.backpressure_num_objects))

        inflight = _InFlightTask(spec)
        with self._tasks_lock:
            self._tasks[spec.task_id] = inflight

        if spec.kind == TaskKind.ACTOR_TASK:
            self._submit_actor_task(spec, inflight, deps)
        else:
            self._submit_with_deps(spec, inflight, deps)
        return refs

    def _submit_with_deps(self, spec: TaskSpec, inflight: _InFlightTask,
                          deps: List[ObjectID]) -> None:
        for d in deps:
            self._ensure_available(d)
        pending = [d for d in deps if not self.futures.is_done(d)]
        inflight.deps_remaining = len(pending)
        if not pending:
            self._schedule(spec, inflight)
            return
        counter_lock = threading.Lock()

        def on_dep_done(_oid):
            with counter_lock:
                inflight.deps_remaining -= 1
                ready = inflight.deps_remaining == 0
            if ready:
                self._schedule(spec, inflight)

        for d in pending:
            self.futures.add_done_callback(d, on_dep_done)

    def _schedule(self, spec: TaskSpec, inflight: _InFlightTask) -> None:
        with inflight.lock:
            if inflight.cancelled:
                return
            inflight.state = TaskState.QUEUED
        from ray_tpu._private.task_spec import PlacementGroupSchedulingStrategy
        if isinstance(spec.scheduling_strategy,
                      PlacementGroupSchedulingStrategy):
            self._schedule_into_pg(spec, inflight)
            return
        try:
            node = self.scheduler.pick_node(spec, self.nodes(),
                                            preferred=self._locality_node(spec))
        except SchedulingError as e:
            self._fail_unschedulable(spec, exc.TaskError(e, spec.name))
            return
        inflight.node_id = node.node_id
        node.enqueue(spec)
        self._record_submit_phase(spec, node)

    def _record_submit_phase(self, spec: TaskSpec, node: Node) -> None:
        """submit phase: submit_task entry -> node backlog enqueue
        (dependency waits + scheduler placement)."""
        if getattr(spec, "trace_sampled", False) and spec.submit_mono:
            now = time.perf_counter()
            trace_events.record_phase_rt(
                spec, "submit", now - spec.submit_mono,
                node.node_id.hex(), start_wall=spec.submit_wall,
                end_mono=now)

    def _fail_unschedulable(self, spec: TaskSpec,
                            error: exc.TaskError) -> None:
        """An infeasible placement must fail the ACTOR too, not just the
        creation task: plain _fail_task left the actor RESTARTING
        forever with its method calls buffering (reachable whenever a
        restart's target — e.g. a hard-affinity node — left the
        cluster)."""
        if spec.kind == TaskKind.ACTOR_CREATION:
            self._actor_creation_failed(spec, error)
        else:
            self._fail_task(spec, error)

    def _schedule_into_pg(self, spec: TaskSpec,
                          inflight: _InFlightTask) -> None:
        """Rewrite the demand onto bundle-scoped resources and enqueue."""
        strat = spec.scheduling_strategy
        pg = strat.placement_group
        # The strategy may carry a pickled CLONE of the pg (handle that
        # crossed a worker/object-store boundary): its event is never set
        # by the manager and its bundles are stale — re-bind to the live
        # object by id whenever one exists.
        live = self.pg_manager.get(pg.id)
        if live is not None and live is not pg:
            strat.placement_group = pg = live
        if not pg.is_ready():
            # Queue behind placement; the PG manager sets the event when
            # placed (or removed/unschedulable).
            def wait_then_schedule():
                pg._ready_event.wait()
                self._schedule_into_pg(spec, inflight)
            threading.Thread(target=wait_then_schedule, daemon=True).start()
            return
        if pg.state != "CREATED":
            self._fail_unschedulable(spec, exc.TaskError(
                exc.PlacementGroupUnschedulableError(
                    f"placement group is {pg.state}"), spec.name))
            return
        idx = strat.placement_group_bundle_index
        if idx != -1 and not (0 <= idx < len(pg.bundles)):
            self._fail_unschedulable(spec, exc.TaskError(
                ValueError(
                    f"placement_group_bundle_index={idx} out of range for "
                    f"{len(pg.bundles)} bundles"), spec.name))
            return
        # On a retry the spec's resources are already bundle-scoped; match
        # bundles against the original demand snapshot.
        if spec.pg_demand is None:
            spec.pg_demand = dict(spec.resources)
        demand = spec.pg_demand
        candidates = (pg.bundles if idx == -1 else [pg.bundles[idx]])
        # Prefer bundles on non-draining hosts: a bundle pinned to a
        # draining node is a last resort (the PG re-places when the
        # node finally leaves).
        if idx == -1 and len(candidates) > 1:
            def _bundle_draining(b) -> int:
                n = self.get_node(b.node_id) if b.node_id else None
                return 1 if (n is not None
                             and getattr(n, "draining", False)) else 0
            candidates = sorted(candidates, key=_bundle_draining)
        chosen = None
        for bundle in candidates:
            if all(bundle.resources.get(k, 0.0) >= v - 1e-9
                   for k, v in demand.items()):
                node = self.get_node(bundle.node_id)
                if node is not None and node.alive:
                    avail = node.ledger.available()
                    scoped = {f"_pg_{pg.id.hex()[:16]}_{bundle.index}_{k}": v
                              for k, v in demand.items()}
                    if chosen is None or all(
                            avail.get(k, 0.0) >= v - 1e-9
                            for k, v in scoped.items()):
                        chosen = (bundle, node, scoped)
                        if all(avail.get(k, 0.0) >= v - 1e-9
                               for k, v in scoped.items()):
                            break
        if chosen is None:
            self._fail_unschedulable(spec, exc.TaskError(
                SchedulingError(
                    f"demand {demand} does not fit any bundle of "
                    f"the placement group"), spec.name))
            return
        bundle, node, scoped = chosen
        spec.resources = scoped
        spec.placement_group_id = pg.id
        spec.bundle_index = bundle.index
        spec.pg_capture = bool(
            getattr(strat, "placement_group_capture_child_tasks", False))
        inflight.node_id = node.node_id
        node.enqueue(spec)
        self._record_submit_phase(spec, node)

    def _locality_node(self, spec: TaskSpec) -> Optional[Node]:
        """Prefer the node holding the largest dependency (locality-aware)."""
        # snapshot both tables under their own locks, then do the store
        # size accounting lock-free: _nodes was read here without
        # _nodes_lock (raylint guarded-by), and the per-dep nbytes
        # lookups have no business running under _loc_lock
        with self._nodes_lock:
            nodes = dict(self._nodes)
        with self._loc_lock:
            dep_locs = [(dep, list(self._locations.get(dep, ())))
                        for dep in spec.dependencies()]
        best, best_size = None, 0
        for dep, node_ids in dep_locs:
            for node_id in node_ids:
                node = nodes.get(node_id)
                if node is None or not node.alive:
                    continue
                try:
                    store = node.store
                    if hasattr(store, "nbytes_of"):
                        size = store.nbytes_of(dep)
                    else:
                        size = store._entries[dep].nbytes  # noqa: SLF001
                except KeyError:
                    continue
                if size > best_size:
                    best, best_size = node, size
        return best

    # ------------------------------------------------------------------
    # task execution (runs on node worker threads)
    # ------------------------------------------------------------------
    def _execute_on_node(self, spec: TaskSpec, node: Node) -> None:
        with self._tasks_lock:
            inflight = self._tasks.get(spec.task_id)
        if inflight is not None:
            with inflight.lock:
                if inflight.cancelled:
                    return
                inflight.state = TaskState.RUNNING
        if spec.kind == TaskKind.ACTOR_CREATION:
            self._execute_actor_creation(spec, node)
            return
        self.task_events.record(
            task_id=spec.task_id.hex(), name=spec.name, event="RUNNING",
            node_id=node.node_id.hex())
        from ray_tpu.util import tracing
        with tracing.span(f"task::{spec.name}",
                          task_id=spec.task_id.hex()[:16]):
            self._execute_on_node_traced(spec, node)

    def _execute_on_node_traced(self, spec: TaskSpec, node: Node) -> None:
        try:
            args, kwargs = self._resolve_args(spec)
        except exc.TaskError as te:
            self._finish_task(spec, node, error=te)
            return
        if self._try_process_execute(spec, node, args, kwargs):
            return
        self._execute_inline(spec, node, args, kwargs)

    def _execute_inline(self, spec: TaskSpec, node: Node, args: tuple,
                        kwargs: dict) -> None:
        """In-driver execution: accelerator-plane / in_process work runs
        on the node's (driver-side) dispatch thread — the mesh-owning
        process, with XLA releasing the GIL."""
        token = runtime_context._set_context(
            job_id=spec.job_id or self.job_id, task_id=spec.task_id,
            node_id=node.node_id,
            actor_id=None, resources=spec.resources, task_name=spec.name,
            placement_group_id=spec.placement_group_id,
            pg_capture=spec.pg_capture)
        from ray_tpu.runtime_env import apply_runtime_env
        from ray_tpu.util.rpdb import post_mortem_on_error
        sampled = getattr(spec, "trace_sampled", False)
        t_exec0 = time.perf_counter() if sampled else 0.0
        try:
            with apply_runtime_env(spec.runtime_env), \
                    post_mortem_on_error():
                result = spec.func(*args, **kwargs)
        except BaseException as e:  # noqa: BLE001
            self._finish_task(spec, node,
                              error=exc.TaskError(e, spec.name))
            return
        finally:
            runtime_context._reset_context(token)
            if sampled:
                # exec phase, driver lane (in-process/accelerator work
                # runs in the mesh-owning process, not a worker)
                now = time.perf_counter()
                trace_events.record_phase_rt(
                    spec, "exec", now - t_exec0, node.node_id.hex(),
                    start_wall=trace_events.wall_at(t_exec0),
                    end_mono=now)
        if spec.num_returns in ("streaming", "dynamic") or inspect.isgenerator(
                result):
            self._drain_generator(spec, node, result)
            return
        self._finish_task(spec, node, result=result)

    def _try_process_execute(self, spec: TaskSpec, node: Node,
                             args: tuple, kwargs: dict) -> bool:
        """Route an eligible normal task to a worker process. Returns
        False if the task must run in-process (accelerator-plane work or
        unserializable payload)."""
        from ray_tpu._private.worker_process import WorkerCrashed
        router = self.process_router
        payload = router.eligible_task(spec, args, kwargs)
        if payload is None:
            return False
        try:
            kind, value = router.execute_task(spec, node, payload)
        except WorkerCrashed as crash:
            self._on_process_task_crash(spec, node, crash)
            return True
        if kind == "err":
            with self._tasks_lock:
                inflight = self._tasks.get(spec.task_id)
            if (inflight is not None and inflight.cancelled
                    and isinstance(value, KeyboardInterrupt)):
                # Non-force cancel: the injected KeyboardInterrupt is the
                # cancellation surfacing, not an app error — it must not
                # hit the retry logic nor leak as TaskError(KeyboardInterrupt).
                self._release_task_resources(spec, node)
                self._fail_task(spec, exc.TaskError(
                    exc.TaskCancelledError(spec.task_id), spec.name))
                return True
            self._finish_task(spec, node,
                              error=exc.TaskError(value, spec.name))
        elif (spec.num_returns in ("streaming", "dynamic")
              or kind == "gen"):
            self._drain_generator(spec, node, value)
        else:
            self._finish_task(spec, node, result=value)
        return True

    def _on_process_task_crash(self, spec: TaskSpec, node: Node,
                               crash: Exception) -> None:
        """A worker process died under a task: cancelled → cancelled
        error; otherwise system-failure retry up to max_retries
        (reference: task_manager.h RetryTaskIfPossible on worker death)."""
        with self._tasks_lock:
            inflight = self._tasks.get(spec.task_id)
        cancelled = inflight is not None and inflight.cancelled
        self._release_task_resources(spec, node)
        if cancelled:
            self._fail_task(spec, exc.TaskError(
                exc.TaskCancelledError(spec.task_id), spec.name))
            return
        oom = self.memory_monitor.was_oom_killed(spec.task_id)
        fast_lane = bool(getattr(crash, "fast_lane", False))
        if not oom and fast_lane:
            # lane workers' task ids live in the native core: attribute
            # by claiming ONE recent un-attributed monitor kill, scoped
            # to lane crashes only so a classic worker's segfault near
            # a lane OOM kill is never mislabeled
            oom = self.memory_monitor.consume_unattributed_kill()
        if not oom and node is not None:
            # remote workers are policed by THEIR node's monitor (the
            # raylet role): ask the daemon whether this crash was its
            # OOM kill. The fast_lane flag rides along so the daemon
            # only takes its un-attributed-kill fallback for lane
            # crashes — a classic segfault must not consume a lane
            # crash's OOM entry.
            daemon = getattr(node, "daemon", None)
            if daemon is not None and not daemon.dead:
                try:
                    oom = daemon.client.call(
                        "oom_check", task_id=spec.task_id.hex(),
                        fast_lane=fast_lane, timeout=5.0)["oom"]
                except Exception:
                    pass
        if _retries_left(spec):
            self.task_events.record(task_id=spec.task_id.hex(),
                                    name=spec.name,
                                    event="RETRY_OOM" if oom else "RETRY")
            self._retry(spec)
            return
        if oom:
            self._fail_task(spec, exc.TaskError(
                exc.OutOfMemoryError(
                    f"task {spec.name} was killed by the memory monitor "
                    f"({self.memory_monitor.kills} kills; limit "
                    f"{self.memory_monitor.limit >> 20} MiB) and "
                    f"exhausted its retries"), spec.name))
            return
        self._fail_task(spec, exc.TaskError(
            exc.WorkerCrashedError(str(crash)), spec.name))

    def _resolve_args(self, spec: TaskSpec) -> Tuple[tuple, dict]:
        def resolve(a):
            if isinstance(a, ObjectRef):
                value = self._get_one(a, deadline=None)
                if isinstance(value, exc.TaskError):
                    raise value
                if isinstance(value, exc.RayTpuError):
                    raise exc.TaskError(value, spec.name)
                return value
            return a

        try:
            args = tuple(resolve(a) for a in spec.args)
            kwargs = {k: resolve(v) for k, v in spec.kwargs.items()}
        except exc.TaskError:
            raise
        except exc.RayTpuError as e:
            raise exc.TaskError(e, spec.name)
        return args, kwargs

    def _finish_task(self, spec: TaskSpec, node: Optional[Node],
                     result: Any = None,
                     error: Optional[exc.TaskError] = None) -> None:
        if node is not None and not node.alive:
            # Node "died" while the thread was still running: results are
            # lost with the node; retry is handled by on_node_task_lost.
            self.on_node_task_lost(spec, node)
            return
        if error is not None:
            self.task_events.record(task_id=spec.task_id.hex(),
                                    name=spec.name, event="FAILED")
            if self._maybe_retry_app_error(spec, error):
                return
            self._fail_task(spec, error)
            return
        t_result = (time.perf_counter()
                    if getattr(spec, "trace_sampled", False) else 0.0)
        self.task_events.record(task_id=spec.task_id.hex(),
                                name=spec.name, event="FINISHED")
        # Release the task's resources BEFORE completing the futures: a
        # driver unblocked by get() must observe the node's ledger already
        # freed, or back-to-back submit-after-get races see the node as
        # busy and locality-biased scheduling scatters (the node's
        # dispatch `finally` skips the release via the spec flag).
        self._release_task_resources(spec, node)
        values: List[Any]
        n = spec.num_returns
        if n == 1 or not isinstance(n, int):
            values = [result]
        elif n == 0:
            values = []
        else:
            if not isinstance(result, (tuple, list)) or len(result) != n:
                self._fail_task(spec, exc.TaskError(
                    ValueError(f"task declared num_returns={n} but returned "
                               f"{type(result).__name__}"), spec.name))
                return
            values = list(result)
        for oid, value in zip(spec.return_ids, values):
            self._store_value(oid, value, prefer_node=node)
            self.futures.complete(oid)
        if t_result:
            # result phase: outcome in hand -> return futures completed
            now = time.perf_counter()
            trace_events.record_phase_rt(
                spec, "result", now - t_result,
                node.node_id.hex() if node is not None else "",
                start_wall=trace_events.wall_at(t_result), end_mono=now)
        self._on_task_done(spec, TaskState.FINISHED)

    def _fail_task(self, spec: TaskSpec, error: exc.TaskError) -> None:
        for oid in spec.return_ids:
            self._store_value(oid, error)
            self.futures.complete(oid)
        gen = self._generators.get(spec.task_id)
        if gen is not None:
            gen.finish(error.as_instanceof_cause())
        self._on_task_done(spec, TaskState.FAILED)

    def _on_task_done(self, spec: TaskSpec, state: str) -> None:
        self.stats["tasks_finished"] += 1
        task_hex = spec.task_id.hex()
        # Per-task borrow release (reference: reference_count.h:73): refs
        # the owner created on this task's behalf (nested put/submit from
        # its worker) un-pin NOW — results are already stored, so
        # containment keeps anything the task returned alive. Without
        # this a long-lived daemon pins dead tasks' objects forever.
        backend = getattr(self, "cluster_backend", None)
        svc = getattr(backend, "owner_service", None)
        if svc is not None:
            svc.holder.release("t:" + task_hex)
        # same release for the driver-local fast lane's workers
        self.process_router.release_borrows("t:" + task_hex)
        from ray_tpu._private.export_events import emit_export
        emit_export("TASK", task_id=task_hex, name=spec.name,
                    state=state, kind=str(spec.kind),
                    job_id=self.job_id.hex())
        deps = spec.dependencies()
        if deps:
            self.refcounter.remove_submitted_task_refs(deps)
        with self._tasks_lock:
            inflight = self._tasks.get(spec.task_id)
            if inflight is not None:
                inflight.state = state
                # Drop terminal entries (FINISHED and FAILED both) so the
                # in-flight table doesn't leak specs and their arg pins.
                del self._tasks[spec.task_id]

    def _maybe_retry_app_error(self, spec: TaskSpec,
                               error: exc.TaskError) -> bool:
        retry_on = spec.retry_exceptions
        if retry_on is False or not _retries_left(spec):
            return False
        if retry_on is not True:
            try:
                if not isinstance(error.cause, tuple(retry_on)):
                    return False
            except TypeError:
                return False
        self._retry(spec)
        return True

    def on_node_task_lost(self, spec: TaskSpec, node: Node) -> None:
        """A node died holding this queued/running task (system failure)."""
        if _retries_left(spec):
            self._retry(spec)
        else:
            self._fail_task(spec, exc.TaskError(
                exc.NodeDiedError(
                    f"task {spec.name} lost to death of node "
                    f"{node.node_id.hex()[:8]} and retries exhausted"),
                spec.name))

    def _retry(self, spec: TaskSpec) -> None:
        self.stats["tasks_retried"] += 1
        from ray_tpu._private import failpoints as _fp
        from ray_tpu._private.retry import TASK_RETRY, record_retry
        if _fp.ENABLED:
            # ANY injected error turns the would-be retry into a
            # terminal failure (an escape here would leave the task
            # neither retried nor failed, futures hanging); delay arm
            # stretches the retry storm
            try:
                _fp.fire("worker.retry", task=spec.task_id.hex(),
                         attempt=spec.attempt_number)
            except Exception as e:  # noqa: BLE001 — routed to the task
                self._fail_task(spec, exc.TaskError(e, spec.name))
                return
        # unified backoff before the resubmit (exponential, full
        # jitter, short caps): a crash-looping task must not hammer the
        # scheduler, and the attempt shows up in the retry counters.
        # The wait is DEFERRED, never a blocking sleep: node-death
        # fans out retries for a whole backlog on one thread, and
        # serialized sleeps there would stall every task behind the
        # ones before it.
        backoff = TASK_RETRY.backoff_s(spec.attempt_number)
        record_retry("worker.task_retry", backoff)
        if backoff >= 0.01:
            # ONE shared timer thread services every deferred retry: a
            # node-death fan-out over a 10k-task backlog must not spawn
            # 10k Timer threads (thread exhaustion raises out of the
            # crash-handling path). A resubmit that raises must fail
            # the task — the wheel's own backstop would silently drop
            # it and leave its futures hanging forever.
            from ray_tpu._private.retry import defer

            def fire_retry(spec=spec):
                try:
                    self._resubmit_retry(spec)
                except Exception as e:  # noqa: BLE001 — routed to task
                    try:
                        self._fail_task(spec, exc.TaskError(e, spec.name))
                    except Exception:
                        pass

            defer(backoff, fire_retry)
            return
        self._resubmit_retry(spec)

    def _resubmit_retry(self, spec: TaskSpec) -> None:
        if self._shutdown:
            return
        respec = _clone_spec_for_retry(spec)
        # ONE critical section for check + replace: a gap between the
        # pop and the reinsert would hide the task from a concurrent
        # cancel() scan, silently losing the cancel
        with self._tasks_lock:
            old = self._tasks.get(spec.task_id)
            if old is None:
                # terminal state reached during the deferred window
                # (e.g. a force cancel already ran _fail_task and
                # removed the entry): resurrecting it would re-run a
                # body the user was told is cancelled/failed
                return
            if not old.cancelled:
                inflight = _InFlightTask(respec)
                self._tasks[respec.task_id] = inflight
        if old.cancelled:
            # a cancel() landed during the deferred-backoff window: the
            # lane/daemon cancel paths found nothing running, so honor
            # the flag here instead of resurrecting the task
            # (_fail_task's _on_task_done drops the stale entry)
            self._fail_task(spec, exc.TaskError(
                exc.TaskCancelledError(spec.task_id), spec.name))
            return
        deps = respec.dependencies()
        if respec.kind == TaskKind.ACTOR_TASK:
            # Replay on the (possibly restarting) actor, not the task path.
            self._submit_actor_task(respec, inflight, deps)
        else:
            self._submit_with_deps(respec, inflight, deps)

    # -- streaming generators ----------------------------------------------
    def _release_task_resources(self, spec: TaskSpec,
                                node: Optional[Node]) -> None:
        """Idempotent early release (runs on the worker thread, strictly
        before the exec pool's own `finally` release). Staged: a batch
        of same-shape completions lands on the ledger under ONE lock
        acquisition (node.stage_release flat-combining)."""
        from ray_tpu._private.task_spec import TaskKind
        if (node is not None and spec.kind != TaskKind.ACTOR_CREATION
                and not getattr(spec, "_resources_released", False)):
            spec._resources_released = True
            node.stage_release(spec.resources)

    def _drain_generator(self, spec: TaskSpec, node: Node, gen) -> None:
        state = self._generators.setdefault(
            spec.task_id, GeneratorState(spec.backpressure_num_objects))
        # On a retry, skip items already reported by the previous attempt
        # (streams are assumed deterministic, as in lineage reconstruction).
        skip = len(state.items)
        from ray_tpu._private import failpoints as _fp
        try:
            for item in gen:
                if _fp.ENABLED:
                    # per-item seam: error arm kills the stream mid-way
                    # (consumer sees a typed error); delay arm throttles
                    _fp.fire("worker.generator_stream",
                             task=spec.task_id.hex())
                if skip > 0:
                    skip -= 1
                    continue
                oid = ObjectID.from_random()
                self._store_value(oid, item, prefer_node=node)
                self.futures.complete(oid)
                ref = ObjectRef(oid, owner_hex=self.worker_id.hex(),
                                task_name=spec.name)
                state.report_item(ref)
        except BaseException as e:  # noqa: BLE001
            from ray_tpu._private.worker_process import WorkerCrashed
            if isinstance(e, WorkerCrashed):
                # System failure mid-stream (worker process died): retry
                # like any other worker crash — already-reported items are
                # skipped on the replay (deterministic streams), matching
                # lineage-reconstruction semantics.
                state.finished = False
                self._on_process_task_crash(spec, node, e)
                return
            te = exc.TaskError(e, spec.name)
            state.finish(te.as_instanceof_cause())
            self._fail_task(spec, te)
            return
        state.finish()
        # The task's own return value is the generator handle sentinel.
        for oid in spec.return_ids:
            self._store_value(oid, _StreamingGeneratorSentinel(spec.task_id))
            self.futures.complete(oid)
        self._on_task_done(spec, TaskState.FINISHED)

    def generator_state(self, task_id: TaskID) -> GeneratorState:
        return self._generators.setdefault(task_id, GeneratorState())

    # ------------------------------------------------------------------
    # actors
    # ------------------------------------------------------------------
    def create_actor(self, spec: TaskSpec,
                     get_if_exists: bool = False) -> ActorID:
        actor_id = spec.actor_id
        info = ActorInfo(
            actor_id=actor_id, name=spec.actor_name,
            namespace=spec.namespace or self.namespace,
            max_restarts=spec.max_restarts,
            max_task_retries=spec.max_task_retries,
            detached=(spec.lifetime == "detached"),
            creation_spec=spec,
            class_name=getattr(spec.func, "__name__", "Actor"),
            method_options=dict(spec.method_options))
        if get_if_exists and spec.actor_name:
            actor_id, created = self.gcs.register_actor_or_get_existing(info)
            if not created:
                return actor_id
        else:
            self.gcs.register_actor(info)
        with self._actor_lock:
            self._actor_pending_tasks[actor_id] = []
        self.submit_task(spec, record_lineage=False)
        return actor_id

    def _execute_actor_creation(self, spec: TaskSpec, node: Node) -> None:
        actor_id = spec.actor_id
        try:
            args, kwargs = self._resolve_args(spec)
        except exc.TaskError as te:
            self._actor_creation_failed(spec, te, node)
            return
        from ray_tpu._private.worker_process import WorkerCrashed
        from ray_tpu._private.cluster import DaemonCrashed
        instance = None
        if getattr(node, "daemon", None) is not None:
            payload = None
            if (inspect.isclass(spec.func)
                    and not _class_is_async(spec.func)
                    and not getattr(spec, "in_process", False)):
                payload = self.process_router._serialize_payload(
                    spec, args, kwargs)
            if payload is not None:
                fid, args_blob = payload
                try:
                    instance = node.daemon.create_actor(spec, fid,
                                                        args_blob)
                    self._remote_actors[spec.actor_id] = node.daemon
                except RemoteWorkerCrashed as e:
                    self._retry_or_fail_creation(spec, node, e)
                    return
                except DaemonCrashed as e:
                    self._on_daemon_crash(node)
                    self._retry_or_fail_creation(spec, node, e)
                    return
                except BaseException as e:  # noqa: BLE001
                    self._actor_creation_failed(
                        spec, exc.TaskError(e, spec.name), node)
                    return
            # unserializable / in_process: fall through and create the
            # instance in the driver (mesh-owning process)
        actor_payload = None
        if instance is None and getattr(node, "daemon", None) is None:
            actor_payload = self.process_router.eligible_actor(spec, args,
                                                               kwargs)
        if actor_payload is not None:
            try:
                instance = self.process_router.create_actor(
                    spec, node, actor_payload)
            except WorkerCrashed as e:
                self._retry_or_fail_creation(spec, node, e)
                return
            except BaseException as e:  # noqa: BLE001
                self._actor_creation_failed(
                    spec, exc.TaskError(e, spec.name), node)
                return
        if instance is None:
            token = runtime_context._set_context(
                job_id=spec.job_id or self.job_id, task_id=spec.task_id,
                node_id=node.node_id, actor_id=actor_id,
                resources=spec.resources, task_name=spec.name,
                placement_group_id=spec.placement_group_id,
                pg_capture=spec.pg_capture)
            from ray_tpu.runtime_env import apply_runtime_env
            try:
                with apply_runtime_env(spec.runtime_env):
                    instance = spec.func(*args, **kwargs)
            except BaseException as e:  # noqa: BLE001
                self._actor_creation_failed(spec,
                                            exc.TaskError(e, spec.name),
                                            node)
                return
            finally:
                runtime_context._reset_context(token)

        # The actor may have been killed while __init__ was running; do not
        # resurrect it (install nothing, free the lifetime resources).
        info = self.gcs.get_actor_info(actor_id)
        if info is not None and info.state == ActorState.DEAD:
            self.process_router.discard_actor(actor_id)
            if node.alive:
                node.ledger.release(spec.resources)
            for oid in spec.return_ids:
                self._store_value(oid, exc.ActorDiedError(
                    actor_id, info.death_cause or "actor killed"))
                self.futures.complete(oid)
            self._on_task_done(spec, TaskState.FAILED)
            return

        is_async = _class_is_async(type(instance))
        executor = ActorExecutor(
            actor_id, spec.max_concurrency,
            run_task=lambda s, inst: self._execute_actor_task(s, inst, node),
            run_task_async=lambda s, inst: self._execute_actor_task_async(
                s, inst, node),
            concurrency_groups=spec.concurrency_groups)
        executor.start(instance, is_async)
        node.host_actor(executor)
        with self._actor_lock:
            self._actor_executors[actor_id] = executor
            pending = self._actor_pending_tasks.pop(actor_id, [])
        self.gcs.update_actor_state(actor_id, ActorState.ALIVE,
                                    node_id=node.node_id)
        # Creation-task return: the actor handle's readiness object.
        for oid in spec.return_ids:
            self._store_value(oid, actor_id)
            self.futures.complete(oid)
        self._on_task_done(spec, TaskState.FINISHED)
        for pspec in pending:
            executor.submit(pspec)

    def _retry_or_fail_creation(self, spec: TaskSpec, node: Node,
                                e: BaseException) -> None:
        """System failure (worker process / daemon died during __init__):
        restart semantics, not permanent death — a transient OOM/SIGKILL
        must behave like the post-creation worker-failure path
        (reference: GcsActorManager worker-failure restart)."""
        actor_id = spec.actor_id
        if node.alive:
            node.ledger.release(spec.resources)
        info = self.gcs.get_actor_info(actor_id)
        if (info is not None
                and (info.max_restarts == -1
                     or info.num_restarts < info.max_restarts)):
            self.stats["actor_restarts"] += 1
            info.num_restarts += 1
            self.gcs.update_actor_state(actor_id, ActorState.RESTARTING)
            respec = _clone_spec_for_retry(spec)
            respec.actor_id = actor_id
            with self._tasks_lock:
                inflight = _InFlightTask(respec)
                self._tasks[respec.task_id] = inflight
            self._submit_with_deps(respec, inflight, respec.dependencies())
            return
        self._actor_creation_failed(spec, exc.TaskError(e, spec.name),
                                    node)

    def _actor_creation_failed(self, spec: TaskSpec, error: exc.TaskError,
                               node: Optional[Node] = None) -> None:
        actor_id = spec.actor_id
        if node is not None and node.alive:
            node.ledger.release(spec.resources)
        self.gcs.update_actor_state(actor_id, ActorState.DEAD,
                                    death_cause=str(error.cause))
        with self._actor_lock:
            pending = self._actor_pending_tasks.pop(actor_id, [])
        self._fail_task(spec, error)
        died = exc.ActorError(
            exc.ActorDiedError(actor_id,
                               f"actor __init__ failed: {error.cause!r}"),
            spec.name, actor_id)
        for pspec in pending:
            self._fail_task(pspec, died)

    def _submit_actor_task(self, spec: TaskSpec, inflight: _InFlightTask,
                           deps: List[ObjectID]) -> None:
        actor_id = spec.actor_id
        info = self.gcs.get_actor_info(actor_id)
        if info is None:
            self._fail_task(spec, exc.TaskError(
                ValueError(f"unknown actor {actor_id}"), spec.name))
            return
        if info.state == ActorState.DEAD:
            self._fail_task(spec, exc.ActorError(
                exc.ActorDiedError(actor_id, info.death_cause or "actor died"),
                spec.name, actor_id))
            return

        for d in deps:
            self._ensure_available(d)
        pending = [d for d in deps if not self.futures.is_done(d)]
        if not pending:
            self._enqueue_actor_task_when_ready(spec)
            return
        inflight.deps_remaining = len(pending)
        counter_lock = threading.Lock()

        def on_dep_done(_oid):
            with counter_lock:
                inflight.deps_remaining -= 1
                ready = inflight.deps_remaining == 0
            if ready:
                self._enqueue_actor_task_when_ready(spec)

        for d in pending:
            self.futures.add_done_callback(d, on_dep_done)

    def _enqueue_actor_task_when_ready(self, spec: TaskSpec) -> None:
        actor_id = spec.actor_id
        with self._actor_lock:
            executor = self._actor_executors.get(actor_id)
            if executor is None:
                info = self.gcs.get_actor_info(actor_id)
                if info is None or info.state == ActorState.DEAD:
                    self._fail_task(spec, exc.ActorError(
                        exc.ActorDiedError(
                            actor_id,
                            (info.death_cause if info else None)
                            or "actor is dead"),
                        spec.name, actor_id))
                    return
                # PENDING or RESTARTING: buffer until alive.
                self._actor_pending_tasks.setdefault(actor_id, []).append(spec)
                return
        if not executor.submit(spec):
            # The executor died but _handle_actor_death hasn't unregistered
            # it yet (node-death and task-retry race): drop the stale
            # executor and re-evaluate against GCS state — the task is
            # buffered if the actor is pending/restarting, failed only on
            # confirmed death (reference: actor_task_submitter resubmits
            # queued tasks across restarts, not failing them on the race).
            with self._actor_lock:
                if self._actor_executors.get(actor_id) is executor:
                    self._actor_executors.pop(actor_id, None)
            self._enqueue_actor_task_when_ready(spec)

    def _execute_actor_task(self, spec: TaskSpec, instance: Any,
                            node: Node) -> None:
        with self._tasks_lock:
            inflight = self._tasks.get(spec.task_id)
        if inflight is not None:
            with inflight.lock:
                if inflight.cancelled:
                    return
                inflight.state = TaskState.RUNNING
        try:
            args, kwargs = self._resolve_args(spec)
        except exc.TaskError as te:
            self._finish_task(spec, node, error=te)
            return
        token = runtime_context._set_context(
            job_id=spec.job_id or self.job_id, task_id=spec.task_id,
            node_id=node.node_id,
            actor_id=spec.actor_id, resources=spec.resources,
            task_name=spec.name,
            placement_group_id=spec.placement_group_id,
            pg_capture=spec.pg_capture)
        from ray_tpu._private.worker_process import _ProcessActorInstance
        from ray_tpu._private.cluster import (DaemonCrashed,
                                              RemoteActorInstance,
                                              RemoteWorkerCrashed)
        try:
            if isinstance(instance, RemoteActorInstance):
                import cloudpickle as _cp
                try:
                    kind, result = instance.call_actor_method(
                        spec, _cp.dumps((args, kwargs)))
                except (DaemonCrashed, RemoteWorkerCrashed) as e:
                    raise exc.ActorDiedError(spec.actor_id, str(e))
                if kind == "err":
                    raise result
                if kind == "stored":
                    # the finally below resets the runtime context
                    daemon_key, nbytes = result
                    node.store.register_remote(spec.return_ids[0],
                                               daemon_key, nbytes)
                    with self._loc_lock:
                        self._locations.setdefault(
                            spec.return_ids[0], set()).add(node.node_id)
                    self.task_events.record(task_id=spec.task_id.hex(),
                                            name=spec.name,
                                            event="FINISHED")
                    self._release_task_resources(spec, node)
                    self.futures.complete(spec.return_ids[0])
                    self._on_task_done(spec, TaskState.FINISHED)
                    return
            elif isinstance(instance, _ProcessActorInstance):
                kind, result = self.process_router.call_actor_method(
                    instance, spec, node, args, kwargs)
                if kind == "err":
                    raise result
            else:
                method = getattr(instance, spec.method_name)
                result = method(*args, **kwargs)
        except _ExitActor:
            self._finish_task(spec, node, result=None)
            self.kill_actor(spec.actor_id, no_restart=True,
                            cause="exit_actor() called")
            return
        except BaseException as e:  # noqa: BLE001
            if (isinstance(e, exc.ActorDiedError)
                    and getattr(node, "draining", False)
                    and self._resubmit_drained_actor_task(spec)):
                # the drain's worker recycle caught this call mid-flight:
                # a planned migration replays it on the new incarnation
                # instead of failing it
                return
            self._finish_task(spec, node, error=exc.ActorError(
                e, spec.name, spec.actor_id))
            return
        finally:
            runtime_context._reset_context(token)
        if inspect.isgenerator(result) or spec.num_returns in (
                "streaming", "dynamic"):
            self._drain_generator(spec, node, result)
            return
        self._finish_task(spec, node, result=result)

    def _resubmit_drained_actor_task(self, spec: TaskSpec) -> bool:
        """Replay an actor task whose worker was recycled by a graceful
        drain. Only while the actor is still restartable — a genuinely
        DEAD actor keeps the normal failure surface."""
        info = self.gcs.get_actor_info(spec.actor_id)
        if info is None or info.state == ActorState.DEAD:
            return False
        with self._tasks_lock:
            inflight = self._tasks.get(spec.task_id)
        if inflight is None:
            return False
        with inflight.lock:
            if inflight.cancelled:
                return False
        self._submit_actor_task(spec, inflight, spec.dependencies())
        return True

    async def _execute_actor_task_async(self, spec: TaskSpec, instance: Any,
                                        node: Node) -> None:
        with self._tasks_lock:
            inflight = self._tasks.get(spec.task_id)
        if inflight is not None:
            with inflight.lock:
                if inflight.cancelled:
                    return
                inflight.state = TaskState.RUNNING
        try:
            args, kwargs = self._resolve_args(spec)
        except exc.TaskError as te:
            self._finish_task(spec, node, error=te)
            return
        token = runtime_context._set_context(
            job_id=spec.job_id or self.job_id, task_id=spec.task_id,
            node_id=node.node_id,
            actor_id=spec.actor_id, resources=spec.resources,
            task_name=spec.name,
            placement_group_id=spec.placement_group_id,
            pg_capture=spec.pg_capture)
        try:
            method = getattr(instance, spec.method_name)
            result = method(*args, **kwargs)
            if inspect.iscoroutine(result):
                result = await result
        except _ExitActor:
            runtime_context._reset_context(token)
            self._finish_task(spec, node, result=None)
            self.kill_actor(spec.actor_id, no_restart=True,
                            cause="exit_actor() called")
            return
        except BaseException as e:  # noqa: BLE001
            runtime_context._reset_context(token)
            self._finish_task(spec, node, error=exc.ActorError(
                e, spec.name, spec.actor_id))
            return
        runtime_context._reset_context(token)
        self._finish_task(spec, node, result=result)

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True,
                   cause: str = "ray_tpu.kill() called") -> None:
        # Order matters: stop the executor FIRST so no queued spec can be
        # dispatched to the worker while/after it is reset and recycled.
        with self._actor_lock:
            executor = self._actor_executors.pop(actor_id, None)
        pending = executor.kill(cause) if executor is not None else []
        self.process_router.discard_actor(actor_id)
        info = self.gcs.get_actor_info(actor_id)
        if info is not None and info.node_id is not None:
            node = self.get_node(info.node_id)
            if node is not None:
                node.evict_actor(actor_id)
        self._handle_actor_death(actor_id, cause, pending_tasks=pending,
                                 may_restart=not no_restart)

    def on_actor_worker_died(self, actor_id: ActorID, cause: str) -> None:
        """An actor's worker PROCESS died unexpectedly (crash/kill -9):
        actor-death semantics with restart (reference: GcsActorManager
        worker-failure restart path)."""
        with self._actor_lock:
            executor = self._actor_executors.pop(actor_id, None)
        pending = executor.kill(cause) if executor is not None else []
        info = self.gcs.get_actor_info(actor_id)
        if info is not None and info.node_id is not None:
            node = self.get_node(info.node_id)
            if node is not None:
                node.evict_actor(actor_id)
        self._handle_actor_death(actor_id, cause, pending_tasks=pending,
                                 may_restart=True)

    def _handle_actor_death(self, actor_id: ActorID, cause: str,
                            pending_tasks: List[TaskSpec],
                            may_restart: bool,
                            graceful: bool = False) -> None:
        """``graceful=True`` is the planned-migration variant (node
        drain): the restart neither consumes the actor's max_restarts
        budget nor fails its pending tasks — they replay on the new
        incarnation regardless of max_task_retries."""
        self.process_router.discard_actor(actor_id)
        # Actor-lifetime borrows die with the incarnation (a restart
        # rebuilds state from creation args; the old in-worker refs are
        # gone either way).
        svc = getattr(getattr(self, "cluster_backend", None),
                      "owner_service", None)
        if svc is not None:
            svc.holder.release("a:" + actor_id.hex())
        remote = self._remote_actors.pop(actor_id, None)
        if remote is not None and not remote.dead:
            remote.kill_actor(actor_id, expected=True)
        info = self.gcs.get_actor_info(actor_id)
        if info is None:
            return
        with self._actor_lock:
            self._actor_executors.pop(actor_id, None)
        # Release the actor's lifetime resource hold on its (alive) node.
        if info.node_id is not None and info.creation_spec is not None:
            host = self.get_node(info.node_id)
            if host is not None and host.alive:
                host.ledger.release(info.creation_spec.resources)
                if host.tenancy is not None:
                    # settle the creation's per-job usage (held for the
                    # actor's whole lifetime, see node._run_spec)
                    host.tenancy.note_done(
                        info.creation_spec.job_id.hex()
                        if info.creation_spec.job_id is not None else "",
                        info.creation_spec.resources)
            info.node_id = None
        can_restart = (may_restart and info.creation_spec is not None
                       and (graceful or info.max_restarts == -1
                            or info.num_restarts < info.max_restarts))
        if can_restart:
            self.stats["actor_restarts"] += 1
            if not graceful:    # planned moves don't burn the budget
                info.num_restarts += 1
            self.gcs.update_actor_state(actor_id, ActorState.RESTARTING)
            if graceful or info.max_task_retries != 0:
                # Pending tasks survive the restart and replay on the new
                # incarnation (reference: actor_task_submitter.cc resubmit
                # queue on ConnectActor).
                with self._actor_lock:
                    self._actor_pending_tasks.setdefault(
                        actor_id, []).extend(pending_tasks)
            else:
                for spec in pending_tasks:
                    self._fail_task(spec, exc.ActorError(
                        exc.ActorUnavailableError(
                            f"actor restarting: {cause}"),
                        spec.name, actor_id))
            respec = _clone_spec_for_retry(info.creation_spec)
            respec.actor_id = actor_id
            with self._tasks_lock:
                inflight = _InFlightTask(respec)
                self._tasks[respec.task_id] = inflight
            self._submit_with_deps(respec, inflight, respec.dependencies())
        else:
            self.gcs.update_actor_state(actor_id, ActorState.DEAD,
                                        death_cause=cause)
            err_base = exc.ActorDiedError(actor_id, cause)
            with self._actor_lock:
                buffered = self._actor_pending_tasks.pop(actor_id, [])
            for spec in list(pending_tasks) + buffered:
                self._fail_task(spec, exc.ActorError(err_base, spec.name,
                                                     actor_id))

    # ------------------------------------------------------------------
    # cancellation
    # ------------------------------------------------------------------
    def cancel(self, ref: ObjectRef, force: bool = False,
               recursive: bool = True) -> None:
        while True:
            with self._tasks_lock:
                target = None
                for inflight in self._tasks.values():
                    if ref.id in inflight.spec.return_ids:
                        target = inflight
                        break
            if target is None:
                return
            with target.lock:
                if target.state in (TaskState.FINISHED,
                                    TaskState.FAILED):
                    return
                target.cancelled = True
                was_running = target.state == TaskState.RUNNING
            # a retry resubmit replaces the _tasks entry (same task_id,
            # fresh _InFlightTask): if that happened between our lookup
            # and the flag set, the flag landed on a stale object —
            # re-loop and cancel the live incarnation (converges: a
            # flagged live entry stops the retry chain)
            with self._tasks_lock:
                if self._tasks.get(target.spec.task_id) is target:
                    break
        if was_running:
            # Running in a worker process: force → SIGTERM the process
            # (the crash handler reports TaskCancelledError); non-force →
            # async KeyboardInterrupt into the executing thread.
            if self.process_router.cancel_task(target.spec.task_id, force):
                return
            # Daemon-executed task: forward over the wire (CancelTask,
            # core_worker.proto:525).
            node = self.get_node(target.node_id) if target.node_id else None
            daemon = getattr(node, "daemon", None) if node else None
            if daemon is not None and daemon.cancel_task(
                    target.spec.task_id, force):
                return
        if not was_running or force:
            self._fail_task(target.spec, exc.TaskError(
                exc.TaskCancelledError(target.spec.task_id),
                target.spec.name))

    # ------------------------------------------------------------------
    # debug state (reference: raylet debug_state_*.txt dumps with asio
    # handler stats — common/asio/instrumented_io_context.h)
    # ------------------------------------------------------------------
    def debug_state(self) -> str:
        lines = [f"session: {self.session_dir}",
                 f"stats: {self.stats}",
                 f"tracked refs: {self.refcounter.num_tracked()}",
                 f"lineage entries: {self.lineage.num_entries()}"]
        for node in self.nodes():
            with node._running_lock:
                running = len(node._running)
            lines.append(
                f"node {node.node_id.hex()[:8]}: alive={node.alive} "
                f"running={running} backlog={node._backlog_n} "
                f"actors={len(node.actors)} "
                f"store_used={node.store.used_bytes()} "
                f"loop={node.loop_stats}")
        if self.cluster_backend is not None:
            # which control-plane core each daemon advertised in hello
            lines.extend(self.cluster_backend.describe_peers())
        return "\n".join(lines)

    # ------------------------------------------------------------------
    # shutdown
    # ------------------------------------------------------------------
    def shutdown(self) -> None:
        self._shutdown = True
        from ray_tpu.util import profiling as _profiling
        _profiling.stop_process_sampler()
        self.memory_monitor.stop()
        if self._log_monitor is not None:
            self._log_monitor.stop()  # joins; loop does the final drain
        self.process_router.shutdown()
        if self.cluster_backend is not None:
            try:
                self.cluster_backend.shutdown()
            except Exception:
                pass
        for node in self.nodes():
            node.shutdown(fail_tasks=False)
            node.store.close()
        with self._nodes_lock:
            self._nodes.clear()
        self.memory_store.clear()


class _StreamingGeneratorSentinel:
    def __init__(self, task_id: TaskID):
        self.task_id = task_id


class _ExitActor(BaseException):
    pass


def _class_is_async(cls) -> bool:
    return any(inspect.iscoroutinefunction(m)
               for _, m in inspect.getmembers(cls,
                                              predicate=inspect.isfunction))


def _clone_spec_for_retry(spec: TaskSpec) -> TaskSpec:
    # The task_id is kept stable across attempts (parity: the reference
    # retries under the same TaskID with attempt_number++), so streaming
    # generator consumers and in-flight bookkeeping stay bound to it.
    import copy
    respec = copy.copy(spec)
    respec.attempt_number = spec.attempt_number + 1
    return respec


def _retries_left(spec: TaskSpec) -> bool:
    """max_retries < 0 means unlimited retries (option contract parity)."""
    return spec.max_retries < 0 or spec.attempt_number < spec.max_retries


def _find_nested_refs(value: Any, _depth: int = 0) -> List[ObjectRef]:
    """Shallow recursive scan for ObjectRefs inside standard containers."""
    if _depth > 6:
        return []
    if isinstance(value, ObjectRef):
        return [value]
    out: List[ObjectRef] = []
    if isinstance(value, (list, tuple, set, frozenset)):
        for v in value:
            out.extend(_find_nested_refs(v, _depth + 1))
    elif isinstance(value, dict):
        for k, v in value.items():
            out.extend(_find_nested_refs(k, _depth + 1))
            out.extend(_find_nested_refs(v, _depth + 1))
    return out


def capture_parent_pg_strategy(strategy):
    """Inherit the caller's PG when it asked to capture child tasks."""
    if strategy != "DEFAULT":
        return strategy
    ctx = runtime_context._ctx.get()
    if (ctx is None or not getattr(ctx, "pg_capture", False)
            or ctx.placement_group_id is None):
        return strategy
    rt = global_runtime()
    if rt is None:
        return strategy
    pg = rt.pg_manager.get(ctx.placement_group_id)
    if pg is None:
        return strategy
    from ray_tpu._private.task_spec import PlacementGroupSchedulingStrategy
    return PlacementGroupSchedulingStrategy(
        pg, placement_group_bundle_index=-1,
        placement_group_capture_child_tasks=True)


def init_runtime(**kwargs) -> Runtime:
    global _global_runtime
    with _global_lock:
        if _global_runtime is not None:
            raise RuntimeError("ray_tpu is already initialized")
        _global_runtime = Runtime(**kwargs)
        return _global_runtime


def shutdown_runtime() -> None:
    from ray_tpu._private.config import reset as _cfg_reset
    from ray_tpu._private.export_events import reset_export_logger
    _cfg_reset()
    reset_export_logger()  # next session binds its own dir
    global _global_runtime
    with _global_lock:
        if _global_runtime is not None:
            _global_runtime.shutdown()
            _global_runtime = None
