"""Per-node object store with host and device (HBM) tiers plus disk spilling.

Parity contract (reference plasma store, ``src/ray/object_manager/plasma/``):
immutable objects, size-accounted capacity, eviction of unreferenced entries,
spill-to-disk under pressure with transparent restore, per-object pinning while
referenced.

TPU-first differences:
- A **device tier**: values that are ``jax.Array`` (or pytrees of them) stay
  resident in HBM and are handed to consumers zero-copy. They are never
  serialized through host memory on the local-host path (reference's GPU
  object store, ``python/ray/experimental/gpu_object_manager``, needs NCCL
  transfers for this; on TPU the array is already addressable by every
  consumer of the same process/mesh).
- Host-tier numpy payloads are stored as read-only views so consumers cannot
  mutate shared state (plasma gives the same guarantee via mmap PROT_READ).
"""

from __future__ import annotations

import os
import pickle
import sys
import threading
import weakref
from collections import OrderedDict
from dataclasses import dataclass
from typing import Any, Dict, Optional

from ray_tpu._private.ids import NodeID, ObjectID
from ray_tpu.exceptions import OutOfMemoryError


# Values of these exact types need no deep walk: drain-path profiles
# showed _nbytes_of + _is_device_value re-walking every stored task
# result (~100us/result on sandboxed kernels for a bare None — the
# in-function imports and jax.tree_map dominate, not the data).
_TRIVIAL_TYPES = (type(None), bool, int, float)


def _nbytes_of(value: Any) -> int:
    """Best-effort deep size estimate without serializing."""
    t = type(value)
    if t in _TRIVIAL_TYPES:
        # int is arbitrary-precision — getsizeof (one cheap C call)
        # keeps a huge int honestly accounted so eviction/OOM
        # thresholds still trigger; the others are fixed-size
        return sys.getsizeof(value) if t is int else 32
    import numpy as np

    seen = set()

    def sz(v) -> int:
        vid = id(v)
        if vid in seen:
            return 0
        seen.add(vid)
        if isinstance(v, np.ndarray):
            return int(v.nbytes)
        tname = type(v).__module__
        if tname.startswith("jax"):
            nb = getattr(v, "nbytes", None)
            if nb is not None:
                return int(nb)
        if isinstance(v, (bytes, bytearray, memoryview)):
            return len(v)
        if isinstance(v, str):
            return len(v)
        if isinstance(v, (list, tuple, set, frozenset)):
            return sys.getsizeof(v) + sum(sz(x) for x in v)
        if isinstance(v, dict):
            return sys.getsizeof(v) + sum(sz(k) + sz(x) for k, x in v.items())
        return sys.getsizeof(v, 64)

    return sz(value)


def _is_device_value(value: Any) -> bool:
    """True if the value is a jax.Array or a pytree containing one."""
    import sys as _sys
    if type(value) in _TRIVIAL_TYPES or isinstance(value, (str, bytes,
                                                           bytearray)):
        return False    # never a device array; skip the tree walk
    if "jax" not in _sys.modules:
        return False    # no jax imported -> no jax.Array can exist
    try:
        import jax
    except ImportError:
        return False
    found = False

    def check(leaf):
        nonlocal found
        if isinstance(leaf, jax.Array):
            found = True
        return leaf

    try:
        jax.tree_util.tree_map(check, value)
    except Exception:
        return False
    return found


def _freeze_numpy(value: Any) -> Any:
    """Make top-level numpy arrays read-only (immutability guarantee)."""
    import numpy as np

    if isinstance(value, np.ndarray):
        v = value.view()
        v.flags.writeable = False
        return v
    return value


@dataclass
class ObjectEntry:
    value: Any
    nbytes: int
    device_tier: bool = False
    spilled_path: Optional[str] = None
    pinned: int = 0  # pin count: >0 means not evictable/spillable
    # native shm tier: (dtype, shape) of the array parked in the C++ store
    native_meta: Optional[tuple] = None
    # explicit tier (host-shm | device-hbm | spilled): drives the
    # ray_tpu_object_store_bytes{tier} occupancy accounting
    tier: str = "host-shm"


# numpy arrays at least this large go to the native shm arena when built
NATIVE_TIER_MIN_BYTES = 64 * 1024


class LocalObjectStore:
    """Size-accounted object store for one (virtual) node."""

    def __init__(self, node_id: NodeID, capacity_bytes: int,
                 spill_dir: Optional[str] = None):
        self.node_id = node_id
        self.capacity_bytes = capacity_bytes
        self._spill_dir = spill_dir
        from ray_tpu._private.lock_sanitizer import tracked_lock
        self._lock = tracked_lock("object_store")
        # insertion-ordered for LRU-ish spilling
        #: guarded by self._lock
        self._entries: "OrderedDict[ObjectID, ObjectEntry]" = OrderedDict()
        self._used = 0                  #: guarded by self._lock
        self.stats = {"puts": 0, "gets": 0, "spills": 0, "restores": 0,
                      "evictions": 0, "native_puts": 0}
        # explicit (host-shm | device-hbm | spilled) occupancy, chained
        # into the process aggregate -> ray_tpu_object_store_bytes{tier}
        from ray_tpu.objectplane.tiers import store_accounting
        self.tiers = store_accounting()
        # Outstanding zero-copy views into the native arena, per object.
        # The C++ store defers deallocation while refs are held; this
        # count decides whether close() may munmap (see close()).
        self._native_views: Dict[bytes, int] = {}
        # Native C++ shm tier (plasma equivalent): holds large numpy
        # payloads as zero-copy mmap views. Optional — absent without g++.
        self._native = None
        from ray_tpu._private.config import cfg
        if cfg().native_store:
            try:
                from ray_tpu.native_store import ShmObjectStore, available
                if available():
                    self._native = ShmObjectStore(
                        f"rtpu_{os.getpid()}_{node_id.hex()[:8]}",
                        capacity_bytes)
            except Exception:
                self._native = None

    # -- basic ops ---------------------------------------------------------
    def put(self, object_id: ObjectID, value: Any,
            nbytes: Optional[int] = None) -> int:
        with self._lock:
            if object_id in self._entries:
                return self._entries[object_id].nbytes
            size = nbytes if nbytes is not None else _nbytes_of(value)
            device = _is_device_value(value)
            if not device:
                value = _freeze_numpy(value)
            if not device and size > self.capacity_bytes:
                raise OutOfMemoryError(
                    f"object of {size} bytes exceeds store capacity "
                    f"{self.capacity_bytes}")
            entry = ObjectEntry(value=value, nbytes=size, device_tier=device)
            if device:
                entry.tier = "device-hbm"
            if not device:
                native_meta = self._try_native_put(object_id, value, size)
                if native_meta is not None:
                    entry.value = None
                    entry.native_meta = native_meta
                    self.stats["native_puts"] += 1
                else:
                    self._ensure_space(size)
                    self._used += size
            self._entries[object_id] = entry
            self.stats["puts"] += 1
            self.tiers.add(entry.tier, size)
            return size

    def _try_native_put(self, object_id: ObjectID, value: Any,
                        size: int) -> Optional[tuple]:
        """Park a large contiguous numpy array in the C++ shm arena."""
        import numpy as np

        if (self._native is None or not isinstance(value, np.ndarray)
                or size < NATIVE_TIER_MIN_BYTES
                or value.dtype == object
                or not value.flags.c_contiguous):
            return None
        from ray_tpu.native_store import ShmStoreFull
        try:
            # pin: this layer's refcounting owns lifetime; native LRU must
            # not evict behind our back (falls back to python tier + disk
            # spill when the arena is full)
            self._native.put(object_id.binary(), value, pin=True)
            return (value.dtype, value.shape)
        except (ShmStoreFull, KeyError):
            return None

    def get(self, object_id: ObjectID) -> Any:
        with self._lock:
            entry = self._entries.get(object_id)
            if entry is None:
                raise KeyError(object_id)
            self._entries.move_to_end(object_id)
            if entry.spilled_path is not None:
                self._restore(object_id, entry)
            self.stats["gets"] += 1
            if entry.native_meta is not None:
                import numpy as np
                dtype, shape = entry.native_meta
                key = object_id.binary()
                # Zero-copy view; the native ref is HELD for the lifetime
                # of the returned array (released by a finalizer), so a
                # later delete() defers deallocation instead of freeing
                # memory user code still reads (plasma client semantics).
                view = self._native.get_view(key)  # increfs
                arr = np.frombuffer(view, dtype=dtype).reshape(shape)
                arr.flags.writeable = False
                self._native_views[key] = self._native_views.get(key, 0) + 1
                weakref.finalize(arr, self._release_native_view, key)
                return arr
            return entry.value

    def _release_native_view(self, key: bytes) -> None:
        """Finalizer for zero-copy native-tier arrays."""
        with self._lock:
            n = self._native_views.get(key, 0) - 1
            if n <= 0:
                self._native_views.pop(key, None)
            else:
                self._native_views[key] = n
            if self._native is not None:
                try:
                    self._native.release(key)
                except Exception:
                    pass

    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            return object_id in self._entries

    def delete(self, object_id: ObjectID) -> None:
        with self._lock:
            entry = self._entries.pop(object_id, None)
            if entry is None:
                return
            if entry.spilled_path:
                try:
                    os.unlink(entry.spilled_path)
                except OSError:
                    pass
            elif entry.native_meta is not None:
                try:
                    self._native.delete(object_id.binary())
                except Exception:
                    pass
            elif not entry.device_tier:
                self._used -= entry.nbytes
            self.tiers.add(entry.tier, -entry.nbytes)

    def pin(self, object_id: ObjectID) -> None:
        with self._lock:
            e = self._entries.get(object_id)
            if e is not None:
                e.pinned += 1

    def unpin(self, object_id: ObjectID) -> None:
        with self._lock:
            e = self._entries.get(object_id)
            if e is not None and e.pinned > 0:
                e.pinned -= 1

    def nbytes_of(self, object_id: ObjectID) -> int:
        """Size cached on the entry at insert time (the same number the
        eviction/spill accounting uses) — never re-walks the value."""
        with self._lock:
            e = self._entries.get(object_id)
            return e.nbytes if e is not None else 0

    def used_bytes(self) -> int:
        with self._lock:
            return self._used

    def tier_bytes(self) -> Dict[str, int]:
        """Occupancy by (host-shm | device-hbm | spilled) tier."""
        return self.tiers.snapshot()

    def object_ids(self):
        with self._lock:
            return list(self._entries.keys())

    def clear(self) -> None:
        with self._lock:
            for oid in list(self._entries):
                self.delete(oid)

    def close(self) -> None:
        """Release the native shm arena (unlinks /dev/shm segment).

        If zero-copy views are still held by user code, only the segment
        NAME is removed — the mapping is left alive so those arrays stay
        valid (munmap would SIGSEGV them)."""
        self.clear()
        if self._native is not None:
            try:
                if self._native_views:
                    self._native.unlink_only()
                else:
                    self._native.close(unlink=True)
            except Exception:
                pass
            self._native = None

    # -- pressure handling -------------------------------------------------
    def _ensure_space(self, size: int) -> None:
        """Spill (pinned) or drop (unpinned) host-tier entries until
        fits. Callers hold self._lock (re-entrant) and so does this:
        the spill scan must see a stable entry table."""
        with self._lock:
            if self._used + size <= self.capacity_bytes:
                return
            # Pass 1: spill least-recently-used spillable entries to
            # disk. Native-tier entries don't count toward _used (the
            # C++ arena accounts for them) and pinned entries are in
            # active use — both are skipped.
            for oid, entry in list(self._entries.items()):
                if self._used + size <= self.capacity_bytes:
                    break
                if (entry.device_tier or entry.spilled_path is not None
                        or entry.native_meta is not None
                        or entry.pinned > 0):
                    continue
                if self._spill_dir is not None:
                    self._spill(oid, entry)
            if self._used + size > self.capacity_bytes:
                raise OutOfMemoryError(
                    f"object store on node {self.node_id.hex()[:8]} "
                    f"full: need {size}, used "
                    f"{self._used}/{self.capacity_bytes} "
                    f"and nothing left to spill")

    def _spill(self, object_id: ObjectID, entry: ObjectEntry) -> None:
        with self._lock:    # re-entrant: callers already hold it
            os.makedirs(self._spill_dir, exist_ok=True)
            path = os.path.join(self._spill_dir, object_id.hex())
            with open(path, "wb") as f:
                pickle.dump(entry.value, f, protocol=5)
            entry.spilled_path = path
            entry.value = None
            self._used -= entry.nbytes
            self.stats["spills"] += 1
            self.tiers.move(entry.tier, "spilled", entry.nbytes)
            entry.tier = "spilled"

    def _restore(self, object_id: ObjectID, entry: ObjectEntry) -> None:
        with self._lock:    # re-entrant: callers already hold it
            # Make room FIRST, while the entry is still in spilled
            # state: the scan skips spilled entries, so it can never
            # pick the one being restored (re-spilling it handed the
            # caller value=None), and a failure here leaves the store
            # untouched — spill file intact, _used consistent, a later
            # retry can succeed once pressure drops.
            self._ensure_space(entry.nbytes)
            with open(entry.spilled_path, "rb") as f:
                entry.value = pickle.load(f)
            try:
                os.unlink(entry.spilled_path)
            except OSError:
                pass
            entry.spilled_path = None
            self._used += entry.nbytes
            self.stats["restores"] += 1
            self.tiers.move("spilled", "host-shm", entry.nbytes)
            entry.tier = "host-shm"
