"""Binary identifiers for cluster entities.

Capability parity with the reference's ID scheme (``src/ray/common/id.h``):
every cluster entity (node, job, task, actor, object, placement group) is
identified by a fixed-width random binary ID with a stable hex rendering.
Unlike the reference we do not embed lineage structure in the ID bytes; the
owner/lineage tables in :mod:`ray_tpu._private.refcount` carry that
relationship instead, which keeps IDs opaque and cheap to generate.
"""

from __future__ import annotations

import os
import threading

_counter_lock = threading.Lock()
_counter = 0

# Amortized entropy pool: one os.urandom syscall refills ~1k IDs. The
# per-call syscall dominated ID creation on sandboxed kernels (three IDs
# per task submission put it squarely on the control-plane hot path);
# the reference sidesteps the same cost by deriving most IDs from a
# per-process seed + counter (src/ray/common/id.cc).
_POOL_SIZE = 16384
_pool = b""
_pool_off = 0
_pool_pid = 0
_pool_lock = threading.Lock()


def _refill_locked() -> None:
    global _pool, _pool_off, _pool_pid
    _pool = os.urandom(_POOL_SIZE)
    _pool_off = 0
    _pool_pid = os.getpid()


def _unique_bytes(nbytes: int) -> bytes:
    global _pool_off
    with _pool_lock:
        # pid check: a forked child sharing the parent's buffered bytes
        # would mint the PARENT'S ids — refill from the kernel instead
        # (register_at_fork below handles the common path; the pid check
        # covers forks that bypass os.fork hooks)
        if _pool_off + nbytes > len(_pool) or _pool_pid != os.getpid():
            _refill_locked()
        out = _pool[_pool_off:_pool_off + nbytes]
        _pool_off += nbytes
    return out


def _drop_pool_after_fork() -> None:
    global _pool, _pool_off
    _pool = b""
    _pool_off = 0
    try:
        # the fork snapshotted the lock in its (held) pre-fork state;
        # release our copy or the child's first ID mint deadlocks
        _pool_lock.release()
    except RuntimeError:
        pass


if hasattr(os, "register_at_fork"):
    # hold the lock ACROSS the fork: a child forked while another
    # thread was mid-mint would otherwise inherit a forever-held lock
    os.register_at_fork(before=_pool_lock.acquire,
                        after_in_parent=_pool_lock.release,
                        after_in_child=_drop_pool_after_fork)


class BaseID:
    """Immutable fixed-width binary identifier."""

    SIZE = 16
    __slots__ = ("_binary", "_hash")

    def __init__(self, binary: bytes):
        if not isinstance(binary, bytes) or len(binary) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} requires {self.SIZE} bytes, got {binary!r}"
            )
        self._binary = binary
        self._hash = hash((type(self).__name__, binary))

    @classmethod
    def from_random(cls) -> "BaseID":
        return cls(_unique_bytes(cls.SIZE))

    @classmethod
    def from_hex(cls, hex_str: str) -> "BaseID":
        return cls(bytes.fromhex(hex_str))

    @classmethod
    def nil(cls) -> "BaseID":
        return cls(b"\x00" * cls.SIZE)

    def is_nil(self) -> bool:
        return self._binary == b"\x00" * self.SIZE

    def binary(self) -> bytes:
        return self._binary

    def hex(self) -> str:
        return self._binary.hex()

    def __hash__(self) -> int:
        return self._hash

    def __eq__(self, other) -> bool:
        return type(other) is type(self) and other._binary == self._binary

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self.hex()[:16]})"

    def __reduce__(self):
        return (type(self), (self._binary,))


class NodeID(BaseID):
    SIZE = 16


class JobID(BaseID):
    SIZE = 8


class TaskID(BaseID):
    SIZE = 16


class ActorID(BaseID):
    SIZE = 16


class ObjectID(BaseID):
    SIZE = 20


class PlacementGroupID(BaseID):
    SIZE = 16


class WorkerID(BaseID):
    SIZE = 16


def next_seqno() -> int:
    """Monotonic process-wide sequence number (actor task ordering)."""
    global _counter
    with _counter_lock:
        _counter += 1
        return _counter
