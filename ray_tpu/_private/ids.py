"""Binary identifiers for cluster entities.

Capability parity with the reference's ID scheme (``src/ray/common/id.h``):
every cluster entity (node, job, task, actor, object, placement group) is
identified by a fixed-width random binary ID with a stable hex rendering.
Unlike the reference we do not embed lineage structure in the ID bytes; the
owner/lineage tables in :mod:`ray_tpu._private.refcount` carry that
relationship instead, which keeps IDs opaque and cheap to generate.
"""

from __future__ import annotations

import os
import threading

_counter_lock = threading.Lock()
_counter = 0


def _unique_bytes(nbytes: int) -> bytes:
    return os.urandom(nbytes)


class BaseID:
    """Immutable fixed-width binary identifier."""

    SIZE = 16
    __slots__ = ("_binary", "_hash")

    def __init__(self, binary: bytes):
        if not isinstance(binary, bytes) or len(binary) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} requires {self.SIZE} bytes, got {binary!r}"
            )
        self._binary = binary
        self._hash = hash((type(self).__name__, binary))

    @classmethod
    def from_random(cls) -> "BaseID":
        return cls(_unique_bytes(cls.SIZE))

    @classmethod
    def from_hex(cls, hex_str: str) -> "BaseID":
        return cls(bytes.fromhex(hex_str))

    @classmethod
    def nil(cls) -> "BaseID":
        return cls(b"\x00" * cls.SIZE)

    def is_nil(self) -> bool:
        return self._binary == b"\x00" * self.SIZE

    def binary(self) -> bytes:
        return self._binary

    def hex(self) -> str:
        return self._binary.hex()

    def __hash__(self) -> int:
        return self._hash

    def __eq__(self, other) -> bool:
        return type(other) is type(self) and other._binary == self._binary

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self.hex()[:16]})"

    def __reduce__(self):
        return (type(self), (self._binary,))


class NodeID(BaseID):
    SIZE = 16


class JobID(BaseID):
    SIZE = 8


class TaskID(BaseID):
    SIZE = 16


class ActorID(BaseID):
    SIZE = 16


class ObjectID(BaseID):
    SIZE = 20


class PlacementGroupID(BaseID):
    SIZE = 16


class WorkerID(BaseID):
    SIZE = 16


def next_seqno() -> int:
    """Monotonic process-wide sequence number (actor task ordering)."""
    global _counter
    with _counter_lock:
        _counter += 1
        return _counter
