"""Shared compile-if-stale + dlopen helper for the native tier.

One place owns the g++ invocation and staleness check; the per-library
modules (native_store.py, cpp_client.py) only declare their prototypes.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import List, Optional

NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))), "native")


def build_native_so(src_name: str, out_name: str,
                    libs: Optional[List[str]] = None) -> Optional[str]:
    """Compile ``native/<src_name>`` into ``native/<out_name>`` when the
    source is newer; returns the .so path or None (no g++ / failure)."""
    src = os.path.join(NATIVE_DIR, src_name)
    out = os.path.join(NATIVE_DIR, out_name)
    if not os.path.exists(src):
        return None
    if os.path.exists(out) and (
            os.path.getmtime(out) >= os.path.getmtime(src)):
        return out
    try:
        subprocess.run(
            ["g++", "-O2", "-fPIC", "-shared", "-std=c++17", "-Wall",
             "-o", out, src, *(libs or [])],
            check=True, capture_output=True, timeout=120)
        return out
    except Exception:
        return None


def load_native_so(src_name: str, out_name: str,
                   libs: Optional[List[str]] = None
                   ) -> Optional[ctypes.CDLL]:
    path = build_native_so(src_name, out_name, libs)
    return ctypes.CDLL(path) if path else None
