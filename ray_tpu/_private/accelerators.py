"""Accelerator detection + visibility plumbing.

Reference: `python/ray/_private/accelerators/tpu.py:15-58` (GKE/GCE
metadata, TPU_VISIBLE_CHIPS, pod topology env vars) and
`util/accelerators/tpu.py` pod helpers. Detection here is env-var and
jax-based; cloud metadata endpoints are stubbed (zero-egress image).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

TPU_VISIBLE_CHIPS_ENV = "TPU_VISIBLE_CHIPS"
TPU_ACCELERATOR_TYPE_ENV = "TPU_ACCELERATOR_TYPE"   # e.g. "v5p-64"
TPU_WORKER_ID_ENV = "TPU_WORKER_ID"
TPU_NAME_ENV = "TPU_NAME"


def detect_tpu_chips() -> int:
    """Number of TPU chips visible to this process."""
    visible = os.environ.get(TPU_VISIBLE_CHIPS_ENV)
    if visible:
        return len([c for c in visible.split(",") if c.strip()])
    try:
        import jax
        return len([d for d in jax.devices() if d.platform == "tpu"])
    except Exception:
        return 0


def get_accelerator_type() -> Optional[str]:
    """"v5p-64"-style accelerator type, env or device-kind derived."""
    env = os.environ.get(TPU_ACCELERATOR_TYPE_ENV)
    if env:
        return env
    try:
        import jax
        tpus = [d for d in jax.devices() if d.platform == "tpu"]
        if tpus:
            kind = tpus[0].device_kind.lower().replace(" ", "")
            return f"{kind}-{len(tpus)}"
    except Exception:
        pass
    return None


def get_pod_name() -> Optional[str]:
    return os.environ.get(TPU_NAME_ENV)


def get_worker_id() -> Optional[int]:
    wid = os.environ.get(TPU_WORKER_ID_ENV)
    return int(wid) if wid is not None else None


def set_visible_chips(chip_ids: List[int]) -> None:
    """Scope a worker process to a chip subset (reference:
    set_current_process_visible_accelerator_ids)."""
    os.environ[TPU_VISIBLE_CHIPS_ENV] = ",".join(str(c) for c in chip_ids)


def accelerator_resources() -> Dict[str, float]:
    """Resource dict contribution for node registration."""
    chips = detect_tpu_chips()
    if chips == 0:
        return {}
    res: Dict[str, float] = {"TPU": float(chips)}
    acc_type = get_accelerator_type()
    if acc_type:
        res[f"accelerator_type:{acc_type}"] = 1.0
    return res
