"""Central config/flag system.

Reference capability: the ``RAY_CONFIG(type, name, default)`` X-macro
table (``src/ray/common/ray_config_def.h`` — 219 flags), overridable
per-process via ``RAY_<name>`` env vars and via the ``_system_config``
dict passed at ``ray.init`` (``includes/ray_config.pxi``).

Here every tunable lives in ONE declared table. Resolution order per
flag (highest wins):

1. ``_system_config={...}`` passed to ``ray_tpu.init``
2. ``RAY_TPU_<NAME>`` environment variable
3. the declared default

Usage::

    from ray_tpu._private.config import cfg
    cfg().heartbeat_s            # typed value
    cfg().describe()             # full table with provenance

Subsystems that must read a flag before ``init`` (module import time)
use ``cfg()`` lazily so a later ``_system_config`` is still honored by
anything reading through the accessor.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

_PREFIX = "RAY_TPU_"


def _parse_bool(raw: str) -> bool:
    return raw.strip().lower() not in ("0", "false", "no", "off", "")


@dataclass(frozen=True)
class Flag:
    name: str               # lower_snake; env var is RAY_TPU_<UPPER>
    type: Callable
    default: Any
    doc: str

    @property
    def env_var(self) -> str:
        return _PREFIX + self.name.upper()


# ---------------------------------------------------------------------------
# THE flag table (ray_config_def.h role). Add new tunables here, not as
# ad-hoc os.environ reads.
# ---------------------------------------------------------------------------

FLAG_DEFS = [
    # -- cluster topology / processes --
    Flag("cluster", str, "", "execution topology: '' = in-process virtual "
         "nodes, 'daemons' = head + node-daemon OS processes"),
    Flag("process_pool_size", int, 0, "idle worker-process pool target "
         "(0 = auto: min(4, max(2, cpus//2)))"),
    Flag("process_pool_max", int, 32, "hard cap on the adaptive idle pool "
         "(demand high-water raises the target up to this)"),
    Flag("head_grace_s", float, 20.0, "how long daemons/drivers re-dial a "
         "crashed head before giving up (head FT window)"),
    # -- health / heartbeats --
    Flag("heartbeat_interval_s", float, 0.2, "daemon->head heartbeat period"),
    Flag("node_dead_after_s", float, 1.5, "missed-heartbeat window before "
         "the head declares a node dead"),
    # -- graceful drain / preemption --
    Flag("drain_deadline_s", float, 30.0, "default graceful-drain window: "
         "planned departures (preemption notice, downscale, maintenance) "
         "migrate objects/actors and finish running work for up to this "
         "long before escalating to the hard node-death path"),
    Flag("drain_notice_file", str, "", "path the daemon's preemption "
         "watcher polls; the file appearing (content = reason) triggers "
         "a self-announced graceful drain — the air-gapped stand-in for "
         "the cloud metadata server's maintenance/preemption notice"),
    # -- object plane --
    Flag("native_store", bool, True, "use the C++ shm arena for large "
         "objects (False = pure-dict store)"),
    Flag("pull_chunk", int, 4 << 20, "inter-daemon object transfer chunk "
         "size in bytes (object_buffer_pool role; push and pull share it)"),
    Flag("objectplane_attach", bool, True, "workers (and the same-host "
         "driver) map the node daemon's shm arena and resolve host-tier "
         "objects zero-copy with shared-slot ref/release; False = the "
         "classic per-RPC object path (docs/object_plane.md)"),
    Flag("direct_put_min_bytes", int, 256 * 1024, "puts at/above this "
         "size reserve+write+seal arena space directly — the payload "
         "never rides an RPC/pipe frame; smaller puts stay classic"),
    Flag("raw_tier_min_bytes", int, 64 * 1024, "contiguous numpy arrays "
         "at/above this size store as RAW arena bytes; same-node "
         "consumers get read-only np.frombuffer views with zero "
         "serialization. COUPLED to direct_put_min_bytes: the raw path "
         "rides direct puts, so the effective gate is "
         "max(raw_tier_min_bytes, direct_put_min_bytes) — lower both "
         "to widen zero-copy coverage"),
    Flag("push_prefetch", bool, True, "proactively push task deps to "
         "the consumer's node at dispatch (PushManager: in-flight + "
         "directory + pull dedupe); False = pull-only transfer"),
    Flag("inline_object_size", int, 100 * 1024, "values <= this inline in "
         "the owner memory store (max_direct_call_object_size role)"),
    # -- memory monitor / OOM defense --
    Flag("memory_monitor", bool, True, "enable the host-memory monitor + "
         "worker-killing policies"),
    Flag("memory_monitor_interval", float, 1.0,
         "memory monitor check period (seconds)"),
    Flag("memory_usage_threshold", float, 0.95,
         "fraction of the limit at which the killer engages"),
    Flag("memory_limit_bytes", int, 0, "explicit memory limit "
         "(0 = detect from cgroup/system)"),
    Flag("worker_killing_policy", str, "retriable_fifo",
         "'retriable_fifo' or 'group_by_owner'"),
    # -- memory pressure / graceful degradation (_private/pressure.py,
    # docs/fault_tolerance.md "Memory pressure & graceful degradation") --
    Flag("memory_pressure", bool, False, "arm the per-node "
         "PressureController: fuses host RSS, arena occupancy, and the "
         "spill-dir budget into an ok/soft/hard level — soft spills "
         "cold arena entries proactively and throttles push-prefetch, "
         "hard rejects new reservations/puts with a retriable "
         "MemoryPressureError and feeds pressure-aware placement; off "
         "keeps every put/get hot path byte-identical (zero-overhead-"
         "when-off, same discipline as net_chaos)"),
    Flag("arena_spill_dir", str, "", "directory for spilled host-shm "
         "arena entries; empty = <tmpdir>/rtpu_spill_<arena> created "
         "on first spill"),
    Flag("arena_spill_watermarks", str, "0.70,0.85", "soft,hard arena "
         "occupancy fractions: past soft the controller spills cold "
         "sealed unpinned entries back down to the soft line; past "
         "hard (or when the host monitor is at its own threshold) new "
         "reservations/puts are rejected with MemoryPressureError"),
    Flag("arena_spill_budget_bytes", int, 0, "cap on total bytes parked "
         "in the spill dir (0 = unbounded); at budget the spiller "
         "stops and sustained arena pressure escalates to hard"),
    Flag("pressure_tick_s", float, 0.5, "PressureController evaluation "
         "period (seconds); 0 disarms the controller even when "
         "memory_pressure is on"),
    # -- logs --
    Flag("log_to_driver", bool, True, "capture worker stdout/stderr to "
         "per-pid files and tail them to the driver"),
    Flag("log_dir", str, "", "worker log directory override"),
    # -- observability --
    Flag("export_events", bool, False, "write structured task/actor/node/"
         "job/train/PG lifecycle events as JSONL under the session dir "
         "(export_*.proto role)"),
    Flag("task_trace", bool, True, "stamp a trace context into every "
         "task and record per-phase latency spans (submit/linger/queue/"
         "dispatch/exec/result) on every process; spans feed `ray_tpu "
         "timeline`, util.state.task_breakdown, and the "
         "ray_tpu_task_phase_seconds histogram (docs/observability.md)"),
    Flag("trace_sample", float, 1.0, "fraction of tasks traced when "
         "task_trace is on; sampling is deterministic in the task id so "
         "driver, daemon, and worker agree per task (1.0 = every task)"),
    Flag("profiling_hz", float, 0.0, "continuous stack-sampler rate "
         "(samples/second) in every process — driver, head, daemon, "
         "workers; 0 = off (the default; on-demand bursts via `ray_tpu "
         "profile` / util.state.cluster_profile work either way). "
         "Profiles federate to the head on heartbeats "
         "(docs/observability.md)"),
    Flag("lock_metrics", bool, False, "meter tracked runtime locks: "
         "wait/hold-time histograms (ray_tpu_lock_wait_seconds / "
         "ray_tpu_lock_hold_seconds{lock}) plus a contended counter on "
         "every named lock; mutually exclusive with lock_sanitizer "
         "(sanitizer wins when both are set)"),
    # -- accelerator topology --
    Flag("tpu_topology", str, "", "TPU slice topology for ICI-aware gang "
         "scheduling, '<gen>:<AxBxC>' (e.g. 'v5p:4x4x4'); '' = no "
         "topology (resource-count placement only)"),
    # -- control-plane batching (docs/performance.md) --
    Flag("submit_batch", bool, True, "coalesce driver->daemon task "
         "submissions into push_task_batch wire frames (False = one "
         "submit_task RPC per task, the pre-batching behavior)"),
    Flag("submit_batch_max", int, 64, "max tasks per push_task_batch "
         "frame; the coalescer flushes when this many are queued"),
    Flag("submit_linger_us", int, 200, "how long (microseconds) the "
         "submit coalescer waits for more tasks before flushing a "
         "non-full batch; 0 = flush immediately (batching only under "
         "concurrent submission pressure)"),
    Flag("free_batch_max", int, 256, "max object ids per free_objects "
         "RPC; the zero-ref free buffer flushes when this many are "
         "queued"),
    Flag("free_flush_ms", float, 5.0, "max milliseconds a queued "
         "zero-ref free waits before its buffer is flushed to the "
         "daemon"),
    # -- drain-side result pipeline (docs/performance.md "Result path") --
    Flag("result_batch_max", int, 256, "max task completions per "
         "task_batch_done push frame; the daemon's reply pump flushes "
         "when this many are buffered for one driver connection"),
    Flag("result_linger_us", int, 500, "how long (microseconds) the "
         "daemon's reply pump lingers for more completions before "
         "flushing a non-full task_batch_done frame; 0 = flush "
         "immediately"),
    Flag("exec_pool_size", int, 0, "worker threads in each node's task "
         "execution pool (the dispatch loop feeds admitted tasks to "
         "this sized pool instead of spawning per task); 0 = the "
         "node's max_worker_threads (256)"),
    # -- bench --
    Flag("bench_total_deadline", int, 540, "bench.py total wall-clock "
         "budget (seconds)"),
    # -- sanitizers (SURVEY §5.2: the reference's TSAN-in-CI role) --
    Flag("lock_sanitizer", bool, False, "track runtime lock acquisition "
         "order and warn on inversion cycles (potential deadlocks); "
         "see _private/lock_sanitizer.py"),
    # -- fault injection / retry discipline (_private/failpoints.py,
    # _private/retry.py) --
    Flag("failpoints", str, "", "failpoint spec activating deterministic "
         "fault injection, e.g. 'rpc.client.send=drop:every=3'; also "
         "honored as the RAY_TPU_FAILPOINTS env var by spawned "
         "daemon/head/worker processes"),
    Flag("failpoints_seed", int, 0, "RNG seed for probabilistic "
         "failpoint arms (0 = unseeded); same seed => same schedule"),
    Flag("net_chaos", str, "", "network-chaos link-policy spec "
         "degrading control-plane links deterministically, e.g. "
         "'driver>daemon=drop=0.3;daemon>head=partition:start=500"
         ":dur=2000'; also honored as the RAY_TPU_NET_CHAOS env var "
         "by spawned daemon/head/worker processes "
         "(_private/netchaos.py)"),
    Flag("net_chaos_seed", int, 0, "RNG seed for probabilistic "
         "link-policy draws (0 = unseeded); same seed => same "
         "drop/dup/jitter schedule"),
    Flag("control_call_timeout_s", float, 60.0, "deadline for bounded "
         "control-plane round trips whose reply is an ack, not a task "
         "outcome (batch-submit flush, free flush): a silent one-way "
         "partition surfaces as a typed RpcError instead of a wedged "
         "thread"),
    Flag("retry_base_backoff_s", float, 0.05, "RetryPolicy.default "
         "first-backoff cap (exponential, full jitter)"),
    Flag("retry_max_backoff_s", float, 2.0, "RetryPolicy.default "
         "backoff cap ceiling"),
    Flag("fairshare", bool, False, "multi-tenant fair share: DRF "
         "admission verdicts at submit, per-job quota gates and "
         "deficit-ordered batch admission in node dispatch; off keeps "
         "the dispatch hot path byte-identical (Node.tenancy is None)"),
    Flag("job_default_weight", float, 1.0, "fair-share weight assigned "
         "to jobs that never declared one; deficit quanta are split "
         "proportionally to weight among jobs with pending work"),
    Flag("admission_queue_max", int, 4096, "bounded per-job pending "
         "queue: tasks over quota beyond this many outstanding get a "
         "REJECTED verdict (AdmissionRejectedError) instead of QUEUED"),
    Flag("async_core", bool, True, "single-threaded asyncio control "
         "plane: one event loop per process owns every peer socket "
         "(wire, reply pump, dispatch pass); off falls back to the "
         "thread-per-connection core (kept for one release; mixed "
         "clusters interoperate via the async_core hello bit)"),
    Flag("loop_lag_probe_s", float, 0.25, "interval of the event-loop "
         "lag probe behind ray_tpu_event_loop_lag_seconds (a repeating "
         "call_later measuring scheduled-vs-ran skew); 0 disarms"),
    Flag("loop_slow_callback_s", float, 0.05, "slow-callback watchdog "
         "threshold: loop callbacks (asyncio debug timing) or probe "
         "lag past this many seconds count into "
         "ray_tpu_event_loop_slow_callbacks_total"),
    Flag("async_debug", bool, False, "run the control-plane loop in "
         "asyncio debug mode: per-callback timing feeds the "
         "slow-callback watchdog and logs each offender (dev/test "
         "only; debug mode taxes every callback)"),
]

FLAGS: Dict[str, Flag] = {f.name: f for f in FLAG_DEFS}


class Config:
    """Resolved flag values; refreshed when _system_config changes."""

    def __init__(self, system_config: Optional[Dict[str, Any]] = None):
        self._system = dict(system_config or {})
        unknown = set(self._system) - set(FLAGS)
        if unknown:
            raise ValueError(
                f"unknown _system_config keys: {sorted(unknown)}; "
                f"known flags: {sorted(FLAGS)}")
        self._values: Dict[str, Any] = {}
        self._provenance: Dict[str, str] = {}
        for flag in FLAG_DEFS:
            if flag.name in self._system:
                raw: Any = self._system[flag.name]
                source = "_system_config"
            elif flag.env_var in os.environ:
                raw = os.environ[flag.env_var]
                source = f"env:{flag.env_var}"
            else:
                raw = flag.default
                source = "default"
            if flag.type is bool and isinstance(raw, str):
                value: Any = _parse_bool(raw)
            else:
                value = flag.type(raw)
            self._values[flag.name] = value
            self._provenance[flag.name] = source

    def __getattr__(self, name: str) -> Any:
        try:
            return self._values[name]
        except KeyError:
            raise AttributeError(f"no flag named {name!r}") from None

    def describe(self) -> Dict[str, Dict[str, Any]]:
        return {name: {"value": self._values[name],
                       "source": self._provenance[name],
                       "doc": FLAGS[name].doc}
                for name in self._values}


_lock = threading.Lock()
_config: Optional[Config] = None


def cfg() -> Config:
    global _config
    with _lock:
        if _config is None:
            _config = Config()
        return _config


def apply_system_config(system_config: Optional[Dict[str, Any]]) -> Config:
    """Install the per-init overrides (called from ray_tpu.init)."""
    global _config
    with _lock:
        _config = Config(system_config)
        return _config


def reset() -> None:
    """Drop cached values (shutdown path; env changes re-resolve)."""
    global _config
    with _lock:
        _config = None
