"""pip runtime-env materialization.

Reference capability: `python/ray/_private/runtime_env/pip.py` — a
per-node agent materializes ``runtime_env={"pip": [...]}`` into an
isolated environment before the worker starts. TPU-first shape: workers
share the mesh-owning process (or a pooled process with the same
interpreter), so the environment is materialized as an import PATH, not
a separate interpreter: ``pip install --target`` into a content-
addressed cache directory which ``apply_runtime_env`` prepends to
``sys.path`` for the task's duration.

Offline-first: ``{"pip": {"packages": [...], "find_links": DIR}}`` (or
the ``RAY_TPU_PIP_FIND_LINKS`` env var) installs with ``--no-index``
from a local wheelhouse — no network required. A bare package list
without a wheelhouse falls through to a normal index install, which in
an air-gapped environment fails with pip's own error (honest, not a
silent no-op).
"""

from __future__ import annotations

import hashlib
import json
import os
import subprocess
import sys
import threading
from typing import Any, List, Optional, Tuple

_CACHE_ROOT = os.path.join(os.path.expanduser("~"), ".ray_tpu",
                           "pip_envs")
_lock = threading.Lock()


def _normalize(pip_spec: Any) -> Tuple[List[str], Optional[str]]:
    if isinstance(pip_spec, (list, tuple)):
        packages, find_links = list(pip_spec), None
    elif isinstance(pip_spec, dict):
        packages = list(pip_spec.get("packages", []))
        find_links = pip_spec.get("find_links")
    else:
        raise TypeError(
            f"runtime_env['pip'] must be a list or dict, "
            f"got {type(pip_spec).__name__}")
    find_links = find_links or os.environ.get("RAY_TPU_PIP_FIND_LINKS")
    return packages, find_links


def env_dir_for(pip_spec: Any) -> str:
    packages, find_links = _normalize(pip_spec)
    key = hashlib.sha1(json.dumps(
        [sorted(packages), find_links, sys.version_info[:2]],
        default=str).encode()).hexdigest()[:16]
    return os.path.join(_CACHE_ROOT, key)


def materialize_pip(pip_spec: Any) -> str:
    """Install the requested packages into a cached target dir; returns
    the directory to put on sys.path. Raises RuntimeError with pip's
    output on failure.

    Cross-process safe: each installer works in a private temp dir and
    atomically renames it into place — concurrent workers racing on the
    same env either win the rename or discover the winner's completed
    dir; nobody ever imports from a half-written install."""
    import shutil
    import tempfile

    packages, find_links = _normalize(pip_spec)
    env_dir = env_dir_for(pip_spec)
    marker = os.path.join(env_dir, ".ray_tpu_pip_done")
    with _lock:                       # one installer per process
        if os.path.exists(marker):
            return env_dir
        os.makedirs(_CACHE_ROOT, exist_ok=True)
        tmp = tempfile.mkdtemp(prefix=".install-", dir=_CACHE_ROOT)
        try:
            if packages:
                cmd = [sys.executable, "-m", "pip", "install",
                       "--target", tmp, "--quiet",
                       "--disable-pip-version-check",
                       "--no-warn-script-location"]
                if find_links:
                    cmd += ["--no-index", "--find-links", find_links]
                cmd += packages
                proc = subprocess.run(cmd, capture_output=True,
                                      text=True, timeout=600)
                if proc.returncode != 0:
                    raise RuntimeError(
                        f"pip runtime_env materialization failed "
                        f"(rc={proc.returncode}):\n"
                        f"{proc.stderr.strip()[-2000:]}")
            open(os.path.join(tmp, ".ray_tpu_pip_done"), "w").close()
            try:
                os.rename(tmp, env_dir)       # atomic publish
                tmp = None
            except OSError:
                # another process won the race; its completed env wins
                if not os.path.exists(marker):
                    raise
        finally:
            if tmp is not None:
                shutil.rmtree(tmp, ignore_errors=True)
        return env_dir
