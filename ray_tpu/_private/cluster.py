"""Driver-side cluster backend: real head + node-daemon processes.

This is the deployment shape where the control plane leaves the driver
process: a head (``ray_tpu/_private/head.py``, GCS-equivalent) and N node
daemons (``ray_tpu/_private/daemon.py``, raylet-equivalent) run as
separately spawned OS processes, and every interaction is a typed msgpack
RPC. The driver remains the single controller and object owner
(reference: the driver's core worker owns objects and submits tasks;
``src/ray/core_worker/``), which is also the right shape for TPU SPMD:
gang placement is centrally decided and the accelerator plane never
leaves the mesh-owning process.

What rides the wire (reference contracts):
- worker lease + task push   (node_manager.proto RequestWorkerLease,
  core_worker.proto PushTask)
- PG bundle 2PC              (PrepareBundleResources / Commit / Cancel)
- object get/put/free/pull   (object_manager.proto), with a same-host
  zero-copy path through the C++ shm arena (plasma's fd-passing role)
- worker-initiated core ops  (CoreWorkerService direction: daemons call
  the driver's owner server)
- health                     (daemon→head heartbeats; head long-poll
  pubsub pushes node death to the driver)
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import cloudpickle

from ray_tpu._private import events as _events
from ray_tpu._private import failpoints as _fp
from ray_tpu._private import rpc
from ray_tpu._private import daemon as _daemon_schemas  # noqa: F401 — declares the daemon RPC schemas
from ray_tpu._private.head import HeadClient
from ray_tpu._private.ids import NodeID
from ray_tpu._private.lock_sanitizer import tracked_lock
from ray_tpu._private.rpc import HOLD, Client, Server, declare

declare("core_op", "call", "payload", "task")

INLINE_RESULT = 100 * 1024


def _spawn(module: str, args: List[str],
           output_path: Optional[str] = None
           ) -> Tuple[subprocess.Popen, int]:
    """Spawn a python -m <module> child; returns (proc, announced_port).
    ``output_path`` redirects the child's stdout/stderr to a file —
    REQUIRED when the spawning process's own stdout is a pipe a caller
    waits on (`ray-tpu start`), else the child holds the pipe open."""
    r, w = os.pipe()
    env = dict(os.environ)
    repo_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    env["PYTHONPATH"] = repo_root + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    # Control-plane processes never own the accelerator.
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    out = None
    if output_path is not None:
        out = open(output_path, "ab")
    try:
        proc = subprocess.Popen(
            [sys.executable, "-m", module, *args, "--announce-fd", str(w)],
            pass_fds=(w,), env=env, start_new_session=True,
            stdout=out, stderr=out)
    finally:
        if out is not None:
            out.close()
    os.close(w)
    with os.fdopen(r) as f:
        line = f.readline().strip()
    if not line:
        raise RuntimeError(f"{module} failed to start")
    return proc, int(line)


class ArenaCache:
    """Same-host attach to daemon shm arenas by name (zero-copy reads,
    direct-put writes, and shared-slot ref releases). Attach-only: a
    missing segment (remote host, no native build) caches as failed and
    every object falls back to the RPC byte path."""

    def __init__(self):
        self._arenas: Dict[str, Any] = {}  #: guarded by self._lock
        self._failed: set = set()          #: guarded by self._lock
        self._lock = tracked_lock("cluster.arena_cache", reentrant=False)

    def handle(self, arena: str):
        """Attached ShmObjectStore for ``arena``, or None."""
        with self._lock:
            store = self._arenas.get(arena)
            if store is not None:
                return store
            if arena in self._failed:
                return None
            try:
                from ray_tpu.native_store import ShmObjectStore
                store = ShmObjectStore.attach(arena)
            except Exception:
                self._failed.add(arena)
                return None
            self._arenas[arena] = store
            return store

    def read(self, arena: str, capacity: int, off: int,
             size: int) -> Optional[memoryview]:
        store = self.handle(arena)
        if store is None:
            return None
        return store.read_range(off, size)

    def write(self, arena: str, off: int, payload) -> bool:
        """Fill a daemon-reserved (unsealed) range in place — the
        direct-put payload write; the bytes never ride an RPC frame."""
        store = self.handle(arena)
        if store is None:
            return False
        try:
            store.write_range(off, payload)
            return True
        except Exception:
            return False

    def ext_release(self, arena: str, slot: int) -> bool:
        """Drop a shared-slot object ref through the local mapping (the
        zero-RPC release leg of the ref/release protocol)."""
        store = self.handle(arena)
        if store is None:
            return False
        try:
            store.ext_release(slot)
            return True
        except Exception:
            return False

    def close(self) -> None:
        # Deliberate leak, not munmap: zero-copy views handed to user
        # code may outlive this cluster session, and their finalizers
        # must find a mapping (and a live handle) — see
        # ShmObjectStore.detach_leak. The daemon owns the segment name;
        # nothing here keeps /dev/shm entries alive.
        with self._lock:
            for store in self._arenas.values():
                try:
                    store.detach_leak()
                except Exception:
                    pass
            self._arenas.clear()


class DaemonCrashed(Exception):
    """The daemon PROCESS died (transport failure): node-level failure."""


class RemoteWorkerCrashed(Exception):
    """A worker process inside a (healthy) daemon died under a task."""


class _Stream:
    def __init__(self):
        import queue

        self.q: "queue.Queue" = queue.Queue()


_STREAM_DEAD = object()


class _SubmitCoalescer:
    """Per-destination driver→daemon submit batching.

    Classic-path task submissions (everything that used one
    ``submit_task`` RPC per task) enqueue here; ONE flusher thread per
    daemon drains the queue into ``push_task_batch`` wire frames —
    up to ``submit_batch_max`` tasks per frame, lingering
    ``submit_linger_us`` for stragglers (reference: the batched lease
    requests / coalesced submissions that let Ray survive high task
    rates). Completions come back coalesced on ``task_batch_done``
    push frames, demuxed by :meth:`DaemonHandle._on_push`.

    Retry contract: a flush that fails BEFORE reaching the daemon
    (``batch.submit_flush`` drop/error arms — the deterministic stand-in
    for a lost frame) resends the same batch; the daemon dedupes by task
    id, so a retried frame never double-executes a task.
    """

    _MAX_SEND_ATTEMPTS = 8

    def __init__(self, handle: "DaemonHandle"):
        from ray_tpu._private.config import cfg
        self.handle = handle
        self.batch_max = max(1, int(cfg().submit_batch_max))
        self.linger_s = max(0.0, float(cfg().submit_linger_us) / 1e6)
        self._cv = threading.Condition()
        self._q: deque = deque()           #: guarded by self._cv
        self._stopped = False              #: guarded by self._cv
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"submit-batch-{handle.node_id.hex()[:8]}")
        self._thread.start()

    def enqueue(self, entry: Dict[str, Any]) -> None:
        with self._cv:
            if self._stopped:
                raise DaemonCrashed("daemon handle closed")
            self._q.append(entry)
            # wake the flusher only out of its IDLE wait (first entry)
            # or for a full batch: waking it out of the timed linger on
            # every append would flush 2-element frames and defeat the
            # coalescing the linger exists for
            if len(self._q) == 1 or len(self._q) >= self.batch_max:
                self._cv.notify()

    def stop(self) -> None:
        with self._cv:
            self._stopped = True
            self._cv.notify_all()

    def _loop(self) -> None:
        while True:
            with self._cv:
                while not self._q and not self._stopped:
                    self._cv.wait()
                if self._stopped:
                    return      # waiters are failed by mark_dead
                if (self.linger_s > 0 and len(self._q) < self.batch_max):
                    # one bounded linger for the rest of a burst; a
                    # second wait would add latency, not batching
                    self._cv.wait(self.linger_s)
                n = min(len(self._q), self.batch_max)
                batch = [self._q.popleft() for _ in range(n)]
            if batch:
                self._flush(batch)

    def _flush(self, batch: List[Dict[str, Any]]) -> None:
        handle = self.handle
        # ship each function blob once per (daemon, fid): repeated
        # submissions of the same remote function send only the fid
        # (reference: Ray exports function definitions to GCS once)
        fns: Dict[str, bytes] = {}
        for entry in batch:
            fid = entry["fid"]
            if fid not in handle._fns_shipped and fid not in fns:
                try:
                    from ray_tpu._private.worker_process import \
                        fetch_function_blob
                    fns[fid] = fetch_function_blob(fid)
                except KeyError:
                    pass    # workers fall back to the fetch core op
        for attempt in range(self._MAX_SEND_ATTEMPTS):
            if handle.dead:
                return      # mark_dead already failed the waiters
            if _fp.ENABLED:
                try:
                    act = _fp.fire("batch.submit_flush", n=len(batch),
                                   attempt=attempt)
                except Exception:   # noqa: BLE001 — injected error arm:
                    # the flush attempt "failed in transit"; retry the
                    # same batch (idempotent at the daemon)
                    continue
                if act is _fp.DROP:
                    continue        # frame lost pre-send; retry
            try:
                # linger end is sampled BEFORE the RPC: the phase is
                # "enqueue -> frame leaving on the wire" — measuring
                # after the reply would fold the round trip + daemon
                # frame handling into linger AND double-count it
                # against the daemon's dispatch span
                flush_mono = time.perf_counter()
                # the reply is an enqueue ACK (results ride the pump), so
                # a deadline is safe — an unacked flush past it means a
                # wedged link, which the RpcError path below treats as
                # node death (timeout audit: no unbounded dispatch trips)
                from ray_tpu._private.config import cfg as _cfg
                handle.client.call("push_task_batch", tasks=batch,
                                   fns=fns,
                                   timeout=_cfg().control_call_timeout_s)
                self._record_linger(batch, flush_mono)
            except rpc.RemoteError as e:
                if "no such method" in str(e):
                    # old daemon without the batch handler: fall back
                    # per-task, permanently for this handle
                    handle._batch_supported = False
                    self._flush_per_task(batch)
                    return
                for entry in batch:
                    handle._complete_batch_task(
                        {"task": entry["task"], "e": str(e)})
                return
            except rpc.RpcError:
                handle.mark_dead()      # transport death: node failure
                return
            if fns:
                handle._fns_shipped.update(fns)
            return
        # retries exhausted (persistent injected failure): surface as a
        # daemon-level failure so task retry accounting engages
        handle.mark_dead()

    def _record_linger(self, batch: List[Dict[str, Any]],
                       now: float) -> None:
        """linger phase: coalescer enqueue -> the batch frame leaving on
        the wire, per sampled task (driver lane). ``now`` is the
        pre-send perf_counter reading — one clock read per batch."""
        try:
            from ray_tpu._private import events as _events
            from ray_tpu._private import worker as _worker
            rt = _worker.global_runtime()
            buf = getattr(rt, "task_events", None) if rt else None
            node_hex = self.handle.node_id.hex()
            for entry in batch:
                t_enq = entry.get("t_enq")
                if t_enq is None:
                    continue
                dur = max(now - t_enq, 0.0)
                _events.record_phase(
                    buf, task_id=entry["task"],
                    name=entry.get("name", ""), phase="linger",
                    dur_s=dur, node_id=node_hex, proc="driver",
                    trace_id=entry.get("trace", ""),
                    start_wall=_events.wall_at(t_enq), end_mono=now)
        except Exception:
            pass    # observability must never fail a flush

    def _flush_per_task(self, batch: List[Dict[str, Any]]) -> None:
        """Compatibility path: one submit_task RPC per entry."""
        for entry in batch:
            try:
                out = dict(self.handle.client.call(
                    "submit_task", spec=entry["spec"], fid=entry["fid"],
                    args=entry["args"],
                    backpressure=entry["backpressure"], timeout=None))
                out["task"] = entry["task"]
            except rpc.RemoteError as e:
                out = {"task": entry["task"], "e": str(e)}
            except rpc.RpcError:
                self.handle.mark_dead()
                return
            self.handle._complete_batch_task(out)


class _FreeCoalescer:
    """Buffers zero-ref ``free_objects`` ids per daemon and flushes them
    time/size-bounded (``free_batch_max`` / ``free_flush_ms``) — the
    on-zero callback used to fire one single-element RPC per freed
    object. Frees are idempotent at the daemon, so a flush that fails
    in transit (``batch.free_flush`` failpoint) simply requeues."""

    def __init__(self, handle: "DaemonHandle"):
        from ray_tpu._private.config import cfg
        self.handle = handle
        self.batch_max = max(1, int(cfg().free_batch_max))
        self.flush_s = max(0.0, float(cfg().free_flush_ms) / 1e3)
        self._cv = threading.Condition()
        self._oids: List[bytes] = []       #: guarded by self._cv
        self._stopped = False              #: guarded by self._cv
        self._thread: Optional[threading.Thread] = None  #: guarded by self._cv

    def queue(self, oid: bytes) -> None:
        with self._cv:
            if self._stopped:
                return
            self._oids.append(oid)
            if self._thread is None:    # lazy: most handles never free
                self._thread = threading.Thread(
                    target=self._loop, daemon=True,
                    name=f"free-batch-{self.handle.node_id.hex()[:8]}")
                self._thread.start()
            # first element wakes the idle flusher (it parks in an
            # untimed wait, so a sub-batch_max trickle still leaves
            # within flush_s); later appends ride the timed linger —
            # notifying on each would flush tiny frames; a full batch
            # wakes it early
            if len(self._oids) == 1 or len(self._oids) >= self.batch_max:
                self._cv.notify()

    def _loop(self) -> None:
        while True:
            with self._cv:
                while not self._oids and not self._stopped:
                    self._cv.wait()
                if self._stopped:
                    return
                if len(self._oids) < self.batch_max:
                    # time-bounded: partial batches leave within flush_s
                    self._cv.wait(self.flush_s)
                if self._stopped:
                    return
                oids = self._oids[:self.batch_max]
                del self._oids[:len(oids)]
            if oids:    # a concurrent flush() may have drained the lot
                self._send(oids)

    def flush(self) -> None:
        """Synchronous drain (worker shutdown, node drain): no queued
        free may be lost to a process exit."""
        while True:
            with self._cv:
                oids, self._oids = self._oids, []
            if not oids:
                return
            self._send(oids)

    def stop(self) -> None:
        with self._cv:
            self._stopped = True
            self._oids.clear()      # daemon dead: frees are moot
            self._cv.notify_all()

    def _send(self, oids: List[bytes]) -> None:
        if _fp.ENABLED:
            try:
                act = _fp.fire("batch.free_flush", n=len(oids))
            except Exception:   # noqa: BLE001 — injected error arm
                act = _fp.DROP
            if act is _fp.DROP:
                # flush failed in transit: requeue — object deletion is
                # idempotent at the daemon, so the retry is safe
                with self._cv:
                    if not self._stopped:
                        self._oids[:0] = oids
                return
        try:
            from ray_tpu._private.config import cfg as _cfg
            self.handle.client.call("free_objects", oids=oids,
                                    timeout=_cfg().control_call_timeout_s)
        except (rpc.RpcError, rpc.RemoteError):
            pass    # daemon dead/erroring: its store dies with it


def _count_fenced(kind: str) -> None:
    """Count one result frame rejected by partition fencing.
    ``kind``: "epoch" (stale daemon incarnation), "attempt" (stale task
    attempt), "dead" (stamped frame arrived after mark_dead)."""
    try:
        from ray_tpu.util.metrics import Counter
        Counter("ray_tpu_fenced_results_total",
                "result/stream frames rejected by partition fencing "
                "(stale epoch, stale attempt, or arrival after the "
                "handle was marked dead)",
                tag_keys=("kind",)).inc(tags={"kind": kind})
    except Exception:
        pass    # metrics must never fail result ingest


class DaemonHandle:
    """Driver's connection to one node daemon (lease/push/object plane)."""

    def __init__(self, node_id: NodeID, addr: Tuple[str, int],
                 proc: Optional[subprocess.Popen], arenas: ArenaCache):
        self.node_id = node_id
        self.addr = addr
        self.proc = proc
        self.arenas = arenas
        self._streams: Dict[str, _Stream] = {}  #: guarded by self._slock
        self._slock = tracked_lock("cluster.handle.streams",
                                   reentrant=False)
        self.on_actor_worker_died = None  # set by the backend
        self.client = rpc.connect(addr, timeout=None,
                                  on_push=self._on_push).link(
                                      "daemon", node_id.hex())
        self.dead = False
        # partition fencing: the daemon's registration epoch (minted by
        # the head, learned at hello and refreshed via membership) — a
        # result frame stamped with a LOWER epoch came from a superseded
        # incarnation across a healed partition and must not resolve
        # waiters (docs/fault_tolerance.md "Partitions, epochs & fencing")
        self.epoch = 0
        self._fence_supported = False       # daemon advertises in hello
        self._async_core_remote = False     # which core the daemon runs
        # zero-copy object plane (set from the hello reply)
        self.objectplane = False
        self.arena_name: Optional[str] = None
        self.arena_capacity = 0
        # fast lane: direct submit to the daemon's native (C++) core
        self.fast_port: Optional[int] = None
        self._fast = None
        self._fast_lock = tracked_lock("cluster.handle.fast_rids",
                                       reentrant=False)
        # reconnects (with their backoff sleeps) serialize on their OWN
        # lock: holding _fast_lock through a retry window would stall
        # every concurrent submit's _fast_rids bookkeeping and cancels
        self._fast_dial_lock = tracked_lock("cluster.handle.fast_dial",
                                            reentrant=False)
        # task hex -> (lane client, rid): the CLIENT pins the rid to its
        # generation — a reconnected lane restarts rid numbering, so a
        # bare rid could cancel an unrelated task on the new client
        self._fast_rids: Dict[str, Tuple[Any, int]] = {}  #: guarded by self._fast_lock
        # control-plane batching (submit coalescer + free buffer)
        self._batch_supported = False       # daemon advertises in hello
        self._result_batch = False          # coalesced completions for
        #                                     classic submits (hello flag)
        self._batch: Optional[_SubmitCoalescer] = None
        self._batch_lock = tracked_lock("cluster.handle.batch_init",
                                        reentrant=False)
        self._batch_waiters: Dict[str, list] = {}  #: guarded by self._bw_lock
        self._bw_lock = tracked_lock("cluster.handle.batch_waiters",
                                     reentrant=False)
        self._fns_shipped: set = set()      # fids this daemon holds
        self._free = _FreeCoalescer(self)
        self.runtime = None                    # bound by the backend
        # node memory-pressure level (daemon node_pressure pushes /
        # gossip at join); mirrored onto the runtime Node so pick_node
        # soft-excludes hard-pressure nodes like DRAINING ones
        self.pressure_level = "ok"

    # -- push demux -------------------------------------------------------
    def _on_push(self, method: str, msg: Dict[str, Any]) -> None:
        if method == "task_batch_done":
            # batched completion replies: many task outcomes on one frame
            self._ingest_batch(msg.get("outcomes", ()))
            return
        if method in ("task_yield", "task_stream_end", "task_stream_crash"):
            if self._stale_epoch(msg):
                _count_fenced("epoch")
                return
            with self._slock:
                stream = self._streams.get(msg["task"])
            if stream is not None:
                stream.q.put(msg)
        elif method == "node_pressure":
            self._on_node_pressure(msg.get("level") or "ok")
        elif method == "actor_worker_died":
            cb = self.on_actor_worker_died
            if cb is not None:
                # OFF the reader thread: the death flow issues sync RPCs
                # on THIS client (kill_actor during _handle_actor_death);
                # running it inline would block the reader that must
                # deliver those replies — a deadlock
                threading.Thread(target=cb,
                                 args=(msg["actor_id"], msg["cause"]),
                                 daemon=True,
                                 name="actor-death-cb").start()
        elif method == "worker_log":
            # cross-process worker line surfaced on the driver
            # (reference: print_worker_logs)
            import sys

            out = sys.stderr if msg.get("stream") == "err" else sys.stdout
            print(f"(worker node={msg.get('node', '?')} "
                  f"pid={msg.get('pid')}) {msg.get('line')}", file=out)

    def _on_node_pressure(self, level: str) -> None:
        """Daemon pressure transition: mirror the level onto the
        runtime Node and invalidate the scheduler's feasibility cache
        (the DRAINING discipline — a cached pick must not keep landing
        work on a node that just went hard)."""
        self.pressure_level = level
        rt = self.runtime
        node = rt.get_node(self.node_id) if rt is not None else None
        if node is not None \
                and getattr(node, "pressure_level", "ok") != level:
            node.pressure_level = level
            from ray_tpu._private.scheduler import bump_cluster_epoch
            bump_cluster_epoch()

    def mark_dead(self) -> None:
        self.dead = True
        # fail in-flight RPCs with a typed transport error: a one-way
        # partition (daemon->driver direction lost) would otherwise wedge
        # timeout=None callers (classic submit_task) forever — the head's
        # death-mark is the deadline that lands here. The reader thread
        # stays up, so LATE result pushes still arrive and are counted
        # by the fence (kind="dead") instead of silently vanishing.
        try:
            self.client._fail_all()
        except Exception:
            pass
        with self._slock:
            streams = list(self._streams.values())
        for stream in streams:
            stream.q.put(_STREAM_DEAD)
        batch = self._batch
        if batch is not None:
            batch.stop()
        self._free.stop()
        # fail EVERY batch waiter (queued or in flight): slot[1] stays
        # None, which _submit_batched surfaces as DaemonCrashed
        with self._bw_lock:
            waiters, self._batch_waiters = self._batch_waiters, {}
        for slot in waiters.values():
            slot[0].set()
        fl = self._fast
        if fl is not None:
            fl.close()

    def _stale_epoch(self, msg: Dict[str, Any]) -> bool:
        """True when a frame's ``ep`` stamp is from a SUPERSEDED daemon
        incarnation (the head re-minted the node's epoch since). An
        unstamped frame (pre-fence daemon, or a locally-synthesized
        outcome) is never stale."""
        if not self._fence_supported:
            return False        # pre-fence daemon: nothing is stamped
        ep = msg.get("ep")
        return ep is not None and bool(self.epoch) and ep < self.epoch

    def _complete_batch_task(self, out: Dict[str, Any]) -> None:
        if self._stale_epoch(out):
            _count_fenced("epoch")
            return
        with self._bw_lock:
            task_hex = out.get("task", "")
            slot = self._batch_waiters.get(task_hex)
            if slot is not None:
                att = out.get("att")
                if att is not None and len(slot) > 2 and att != slot[2]:
                    # stale ATTEMPT: leave the slot armed for the live
                    # attempt's outcome
                    slot = None
                else:
                    self._batch_waiters.pop(task_hex, None)
            else:
                att = None
        if slot is not None:
            slot[1] = out
            slot[0].set()
        elif att is not None:
            _count_fenced("attempt")

    def _ingest_batch(self, outcomes) -> None:
        """Ingest one task_batch_done frame WITHOUT re-entering per-task
        code paths: every final outcome's waiter slot pops under ONE
        _bw_lock acquisition and every stream termination resolves its
        queue under ONE _slock acquisition; only then are the events
        set (waking the waiting task threads). Duplicate outcomes (a
        batch.result_flush retry, or out-of-order arrival of a resent
        frame) find no slot and are dropped — exactly-once per task."""
        t0 = time.perf_counter()
        if self.dead:
            # mark_dead already failed every waiter: a STAMPED frame
            # arriving now is a late delivery across a healed partition
            # (or a post-death flush) — count it so chaos campaigns can
            # assert the fence actually engaged
            for out in outcomes:
                if out.get("ep") is not None or out.get("att") is not None:
                    _count_fenced("dead")
            return
        finals = []
        streams = []
        fenced_epoch = 0
        for out in outcomes:
            if self._stale_epoch(out):
                fenced_epoch += 1
                continue
            (streams if out.get("stream") else finals).append(out)
        for _ in range(fenced_epoch):
            _count_fenced("epoch")
        woke = []
        fenced_attempt = 0
        if finals:
            with self._bw_lock:
                for out in finals:
                    task_hex = out.get("task", "")
                    slot = self._batch_waiters.get(task_hex)
                    if slot is None:
                        continue
                    att = out.get("att")
                    if att is not None and len(slot) > 2 and att != slot[2]:
                        # a retried task's slot carries the LIVE attempt
                        # number: an outcome from an earlier attempt
                        # (replayed across a heal) must not resolve it
                        fenced_attempt += 1
                        continue
                    self._batch_waiters.pop(task_hex, None)
                    slot[1] = out
                    woke.append((slot, out))
            for _ in range(fenced_attempt):
                _count_fenced("attempt")
            for slot, _out in woke:
                slot[0].set()
        if streams:
            resolved = []
            with self._slock:
                for out in streams:
                    stream = self._streams.get(out.get("task", ""))
                    if stream is not None:
                        resolved.append((stream, out))
            for stream, out in resolved:
                msg = dict(out)
                msg["m"] = msg.pop("stream")
                stream.q.put(msg)
        self._record_ingest_spans(woke, t0)

    def _record_ingest_spans(self, woke, t0: float) -> None:
        """result_ingest phase: batch frame arrival -> waiters woken
        (driver lane, traced outcomes only)."""
        try:
            traced = [(slot, out) for slot, out in woke
                      if out.get("tr")]
            if not traced:
                return
            now = time.perf_counter()
            node_hex = self.node_id.hex()
            from ray_tpu._private import worker as _worker  # lazy: circular
            rt = _worker.global_runtime()
            buf = getattr(rt, "task_events", None) if rt else None
            for _slot, out in traced:
                tr = out["tr"]
                _events.record_phase(
                    buf, task_id=out.get("task", ""), name=tr[0],
                    phase="result_ingest", dur_s=max(now - t0, 0.0),
                    node_id=node_hex, proc="driver", trace_id=tr[1],
                    start_wall=_events.wall_at(t0), end_mono=now)
        except Exception:
            pass    # observability must never fail an ingest

    def _call(self, method: str, **kw) -> Dict[str, Any]:
        if self.dead:
            raise DaemonCrashed(f"daemon {self.node_id.hex()[:8]} is dead")
        try:
            return self.client.call(method, timeout=None, **kw)
        except rpc.RpcError as e:
            self.mark_dead()
            raise DaemonCrashed(str(e))

    def profile_burst(self, duration: float = 2.0) -> List[Dict[str, Any]]:
        """Stack-sampling burst on this daemon + its pool workers; one
        record per process (the `ray-tpu profile` fan-out leg)."""
        out = self._call("profile_burst", duration=float(duration))
        return [r for r in out.get("procs", []) if isinstance(r, dict)]

    # -- wiring -----------------------------------------------------------
    def hello(self, owner_addr: Tuple[str, int], job_id, namespace: str):
        # ship the driver's import roots (the code-search-path role):
        # module-level functions pickle BY REFERENCE, so daemon workers
        # must be able to import the driver's modules (reference:
        # workers see the job's code paths via working_dir/py_modules)
        import sys as _sys
        sys_path = [p for p in _sys.path
                    if isinstance(p, str) and p
                    and os.path.isdir(p)]
        out = self._call("hello_driver", owner_addr=list(owner_addr),
                         job_id=cloudpickle.dumps(job_id),
                         namespace=namespace, sys_path=sys_path)
        self.fast_port = out.get("fast_port")
        # zero-copy object plane: the daemon's arena, attachable by
        # name when we share its host (direct puts + slot-ref'd gets)
        from ray_tpu._private.config import cfg as _cfg
        self.objectplane = (bool(out.get("objectplane"))
                            and bool(_cfg().objectplane_attach))
        self.arena_name = out.get("arena")
        self.arena_capacity = int(out.get("arena_capacity") or 0)
        # connection-scoped grant-ledger identity: the daemon charges
        # every slot grant / reservation this driver requests to it and
        # reclaims the lot if the connection dies (docs/object_plane.md
        # "crash reclamation")
        self.client_id = out.get("client_id")
        # protocol feature flag: daemons that understand push_task_batch
        # advertise it; anything older gets the per-task wire protocol
        from ray_tpu._private.config import cfg
        self._batch_supported = bool(out.get("batch")) and bool(
            cfg().submit_batch)
        # completions for classic (non-coalesced) submits may return on
        # the task_batch_done pump — independent of submit batching, so
        # a submit_batch=False driver still drains coalesced
        self._result_batch = bool(out.get("result_batch"))
        # fair-share federation: only daemons that advertised the
        # tenancy capability receive tenancy_sync job tables (old
        # daemons simply keep unconditional admission)
        self._tenancy_supported = bool(out.get("tenancy"))
        # partition fencing: epoch/attempt stamps on result frames
        self._fence_supported = bool(out.get("fence"))
        # observational only (frames are core-agnostic): lets cluster
        # stats name which peers run the asyncio core in a mixed fleet
        self._async_core_remote = bool(out.get("async_core"))
        self.epoch = int(out.get("epoch") or 0)
        self._job_id = job_id
        return out

    def _submit_coalescer(self) -> Optional[_SubmitCoalescer]:
        if not self._batch_supported or self.dead:
            return None
        batch = self._batch
        if batch is not None:
            return batch
        with self._batch_lock:
            if self._batch is None and not self.dead:
                self._batch = _SubmitCoalescer(self)
            return self._batch

    def _fast_client(self):
        """Lazily-connected fast-lane client; None when unavailable."""
        if self.fast_port is None or self.dead:
            return None
        fl = self._fast
        if fl is not None and not fl.dead:
            return fl
        with self._fast_dial_lock:
            fl = self._fast
            if fl is not None and not fl.dead:
                return fl                    # a racer reconnected
            port = self.fast_port
            if port is None:
                return None
            from ray_tpu._private.fast_lane import (FastLaneClient,
                                                    lane_reconnect_policy)

            def connect():
                if _fp.ENABLED:
                    _fp.fire("cluster.lane_reconnect",
                             node=self.node_id.hex()[:8])
                return FastLaneClient(
                    (self.addr[0], port),
                    link_id=f"lane:{self.node_id.hex()}")

            try:
                fl = lane_reconnect_policy().run(
                    connect, loop="fast_lane.reconnect",
                    retry_on=(OSError, _fp.FailpointError))
            except (OSError, _fp.FailpointError):
                self.fast_port = None        # core gone: stop retrying
                return None
            self._fast = fl
            return fl

    def _lane_roundtrip(self, fl, spec, submit_fn, gen_kind_handler):
        """ONE lane submit/wait/decode cycle, shared by the plain-task
        and targeted-actor paths. Returns the (kind, value) outcome
        contract, or None when the caller should take the classic path
        (nothing ran here). ``gen_kind_handler(kind, blob)`` resolves
        the path-specific generator kind (fallback vs drained list)."""
        from ray_tpu._private import fast_lane as _fle
        try:
            rid, slot = submit_fn()
        except _fle.FastLaneError:
            # nothing was submitted: safe to fall back
            if self.dead:
                raise DaemonCrashed("daemon died (fast lane)")
            return None
        task_hex = spec.task_id.hex()
        with self._fast_lock:
            self._fast_rids[task_hex] = (fl, rid)
        try:
            kind, blob = fl.wait(slot)
        except _fle.FastLaneUnsubmitted:
            # frame never reached the wire (another submitter's flush
            # failed first): nothing ran — classic path, retry-free
            if self.dead:
                raise DaemonCrashed("daemon died (fast lane)")
            return None
        except _fle.FastLaneError as e:
            # submitted but the lane died before the outcome: the call
            # may have executed — surface as a worker crash so retry
            # accounting (max_retries) decides, never a silent re-run
            if self.dead:
                raise DaemonCrashed(str(e))
            crash = RemoteWorkerCrashed(f"fast lane died mid-call: {e}")
            crash.fast_lane = True
            raise crash
        finally:
            with self._fast_lock:
                self._fast_rids.pop(task_hex, None)
        if kind == _fle.KIND_OK:
            return ("ok", cloudpickle.loads(blob))
        if kind == _fle.KIND_ERR:
            e, tb = cloudpickle.loads(blob)
            setattr(e, "_remote_traceback", tb)
            return ("err", e)
        if kind in (_fle.KIND_GEN_FALLBACK, _fle.KIND_GEN_LIST):
            return gen_kind_handler(kind, blob)
        if kind == _fle.KIND_CANCELLED:
            # same surface as a classic soft cancel: the driver maps a
            # cancelled in-flight KeyboardInterrupt to TaskCancelledError
            return ("err", KeyboardInterrupt())
        if kind == _fle.KIND_CRASHED:
            crash = RemoteWorkerCrashed(blob.decode(errors="replace"))
            # lane workers' task ids live in the C++ core: the OOM
            # check must use the lane-scoped (time-window) attribution
            crash.fast_lane = True
            raise crash
        raise RuntimeError(f"unknown fast-lane outcome kind {kind}")

    def _execute_fast(self, fl, spec, fid: str, args_blob: bytes):
        """Plain-task lane call; the daemon's Python never sees it."""
        from ray_tpu._private import fast_lane as _fle
        payload = _fle.build_payload(
            spec, fid, args_blob,
            getattr(spec, "job_id", None) or getattr(self, "_job_id", None),
            self.node_id)

        def on_gen(kind, blob):
            if kind == _fle.KIND_GEN_LIST:
                # the function body already ran and returned a live
                # generator; the worker drained it in place — replay
                # the items as a stream, never re-run the body
                return ("gen", _fle.replay_gen_list(blob))
            # legacy KIND_GEN_FALLBACK (old worker): classic re-run
            return None

        return self._lane_roundtrip(fl, spec,
                                    lambda: fl.submit(payload), on_gen)

    # -- fused task submit ------------------------------------------------
    def execute_task(self, spec, fid: str, args_blob: bytes):
        """Submit in ONE round trip: the daemon leases a pooled worker,
        pushes the task, and releases the worker itself (streams keep it
        until drained). Returns the same (kind, value) contract as
        ProcessRouter.execute_task. The explicit lease protocol
        (request_worker_lease/push_task/return_worker) stays on the wire
        for callers that pin a worker across calls.

        Plain tasks (NORMAL, single return, no runtime env, not a
        generator function) ride the fast lane — the daemon's native
        C++ core routes them to a dedicated worker with zero daemon
        Python per task."""
        import inspect as _inspect

        from ray_tpu._private.task_spec import TaskKind as _TK
        if (spec.kind == _TK.NORMAL and spec.num_returns == 1
                and not spec.runtime_env
                and not (spec.func is not None
                         and _inspect.isgeneratorfunction(spec.func))):
            fl = self._fast_client()
            if fl is not None:
                out = self._execute_fast(fl, spec, fid, args_blob)
                if out is not None:
                    return out
                # None = lane declined (submit failed, or the function
                # returned a live generator): classic path below. A
                # lane failure AFTER submit never lands here — it
                # raises RemoteWorkerCrashed so the retry accounting
                # (max_retries) applies instead of a silent re-run.
        task_hex = spec.task_id.hex()
        stream = _Stream()
        with self._slock:
            self._streams[task_hex] = stream
        out = None
        try:
            batch = self._submit_coalescer()
            if batch is not None:
                out = self._submit_batched(batch, spec, fid, args_blob)
            elif self._result_batch:
                out = self._submit_via_pump(spec, fid, args_blob)
            else:
                out = self._call(
                    "submit_task", spec=_slim_spec_blob(spec), fid=fid,
                    args=args_blob,
                    backpressure=spec.backpressure_num_objects)
            return self._decode_outcome(out, spec, stream)
        finally:
            if out_is_final(out):
                with self._slock:
                    self._streams.pop(task_hex, None)

    def _submit_batched(self, batch: _SubmitCoalescer, spec, fid: str,
                        args_blob: bytes) -> Dict[str, Any]:
        """Enqueue on the coalescer and wait for the batched completion;
        same outcome dict (and error surface) as the submit_task RPC."""
        task_hex = spec.task_id.hex()
        # slot = [wake event, outcome, live attempt number] — the third
        # element lets the ingest path fence outcomes replayed from an
        # earlier attempt across a healed partition
        slot = [threading.Event(), None, spec.attempt_number]
        with self._bw_lock:
            if self.dead:
                raise DaemonCrashed(
                    f"daemon {self.node_id.hex()[:8]} is dead")
            self._batch_waiters[task_hex] = slot
        entry = {
            "task": task_hex,
            # retries reuse the task id: the daemon's duplicate-frame
            # dedupe keys on (task, attempt) so a retry EXECUTES
            # instead of replaying the previous attempt's outcome
            "attempt": spec.attempt_number,
            "spec": _slim_spec_blob(spec),
            "fid": fid,
            "args": args_blob,
            "backpressure": spec.backpressure_num_objects,
            # opt in to coalesced stream terminations (see
            # _submit_via_pump)
            "term_pump": True,
        }
        if getattr(spec, "trace_sampled", False):
            # linger-phase span inputs — attached ONLY for sampled
            # tasks so unsampled/untraced submissions pay zero extra
            # wire bytes and no clock read (the daemon ignores them)
            entry["t_enq"] = time.perf_counter()
            entry["name"] = spec.name
            entry["trace"] = spec.trace_id
        try:
            batch.enqueue(entry)
        except DaemonCrashed:
            with self._bw_lock:
                self._batch_waiters.pop(task_hex, None)
            raise
        slot[0].wait()
        out = slot[1]
        if out is None:
            raise DaemonCrashed(
                f"daemon {self.node_id.hex()[:8]} died (batched submit)")
        if out.get("e"):
            raise rpc.RemoteError(out["e"])
        return out

    def _submit_via_pump(self, spec, fid: str,
                         args_blob: bytes) -> Dict[str, Any]:
        """Classic per-task submit_task RPC whose COMPLETION returns on
        the coalesced task_batch_done pump (daemon advertised
        ``result_batch`` at hello): the RPC reply is an immediate ack,
        so a submit_batch=False driver still gets batched completion
        delivery — same outcome dict and error surface as the coalesced
        path."""
        task_hex = spec.task_id.hex()
        slot = [threading.Event(), None, spec.attempt_number]
        with self._bw_lock:
            if self.dead:
                raise DaemonCrashed(
                    f"daemon {self.node_id.hex()[:8]} is dead")
            self._batch_waiters[task_hex] = slot
        kw: Dict[str, Any] = {
            "spec": _slim_spec_blob(spec), "fid": fid,
            "args": args_blob,
            "backpressure": spec.backpressure_num_objects,
            "task": task_hex,
            # (task, attempt) dedupe identity, like the batched path
            "attempt": spec.attempt_number,
            "via_pump": True,
            # this driver ingests stream terminations off the pump;
            # without the flag the daemon pushes them per-task (an
            # older driver on a persistent daemon would hang its
            # generator consumers waiting on coalesced terminations
            # its task_batch_done handler drops)
            "term_pump": True,
        }
        if getattr(spec, "trace_sampled", False):
            kw["name"] = spec.name
            kw["trace"] = spec.trace_id
        try:
            out = self._call("submit_task", **kw)
        except BaseException:
            with self._bw_lock:
                self._batch_waiters.pop(task_hex, None)
            raise
        if out.get("outcome") != "pump":
            # daemon ran it inline after all: the reply IS the outcome
            with self._bw_lock:
                self._batch_waiters.pop(task_hex, None)
            return out
        slot[0].wait()
        out = slot[1]
        if out is None:
            raise DaemonCrashed(
                f"daemon {self.node_id.hex()[:8]} died (pumped submit)")
        if out.get("e"):
            raise rpc.RemoteError(out["e"])
        return out

    def _decode_outcome(self, out: Dict[str, Any], spec, stream: _Stream):
        kind = out["outcome"]
        if kind == "crashed":
            # the WORKER died; the daemon itself is healthy
            raise RemoteWorkerCrashed(out["error"])
        if kind == "ok":
            return ("ok", cloudpickle.loads(out["blob"]))
        if kind == "err":
            e, tb = cloudpickle.loads(out["blob"])
            setattr(e, "_remote_traceback", tb)
            return ("err", e)
        if kind == "stored":
            return ("stored", (bytes(out["oid"]), out["nbytes"]))
        if kind == "gen":
            return ("gen", self._stream_iter(spec, stream))
        if kind == "dead":
            raise DaemonCrashed("actor worker is dead")
        raise RuntimeError(f"unknown outcome {kind!r}")

    def _stream_iter(self, spec, stream: _Stream):
        task_hex = spec.task_id.hex()
        try:
            while True:
                msg = stream.q.get()
                if msg is _STREAM_DEAD:
                    raise DaemonCrashed("daemon died mid-stream")
                op = msg["m"]
                if op == "task_yield":
                    yield cloudpickle.loads(msg["blob"])
                    try:
                        self.client.call("gen_ack", task_id=task_hex,
                                         timeout=5.0)
                    except rpc.RpcError:
                        pass
                    continue
                if op == "task_stream_crash":
                    raise RemoteWorkerCrashed(msg["error"])
                if not msg["ok"]:
                    e, tb = cloudpickle.loads(msg["blob"])
                    setattr(e, "_remote_traceback", tb)
                    raise e
                return
        finally:
            with self._slock:
                self._streams.pop(task_hex, None)

    # -- actors -----------------------------------------------------------
    def create_actor(self, spec, fid: str, args_blob: bytes):
        out = self._call("create_actor", spec=_slim_spec_blob(spec),
                         fid=fid, args=args_blob)
        kind = out["outcome"]
        if kind == "crashed":
            # the WORKER died; the daemon itself is healthy
            raise RemoteWorkerCrashed(out["error"])
        if kind == "err":
            e, tb = cloudpickle.loads(out["blob"])
            setattr(e, "_remote_traceback", tb)
            raise e
        return RemoteActorInstance(self, spec.actor_id,
                                   fast_tag=out.get("fast_tag"))

    def _call_actor_fast(self, fl, tag: int, spec, args_blob: bytes):
        """Targeted-lane actor call; returns the (kind, value) contract
        or None when the caller should take the classic path (nothing
        ran here)."""
        from ray_tpu._private import fast_lane as _fle
        payload = _fle.build_actor_payload(
            spec, args_blob,
            getattr(spec, "job_id", None) or getattr(self, "_job_id", None),
            self.node_id)

        def on_gen(kind, blob):
            # the method returned a generator: items were drained in
            # the worker (inside its context + actor lock); replay as a
            # REAL generator so the driver's streaming machinery
            # (inspect.isgenerator -> _drain_generator) engages exactly
            # like the classic path
            return ("gen", _fle.replay_gen_list(blob))

        return self._lane_roundtrip(
            fl, spec, lambda: fl.submit_targeted(tag, payload), on_gen)

    def call_actor_method(self, spec, args_blob: bytes):
        task_hex = spec.task_id.hex()
        stream = _Stream()
        with self._slock:
            self._streams[task_hex] = stream
        out = self._call("call_actor_method", spec=_slim_spec_blob(spec),
                         args=args_blob)
        return self._decode_outcome(out, spec, stream)

    def kill_actor(self, actor_id, expected: bool = True) -> None:
        try:
            self._call("kill_actor", actor_id=actor_id.hex(),
                       expected=expected)
        except DaemonCrashed:
            pass

    def cancel_task(self, task_id, force: bool) -> bool:
        task_hex = task_id.hex()
        if _fp.ENABLED:
            act = _fp.fire("cluster.cancel", task=task_hex)
            if act is _fp.DROP:
                return False        # cancel request lost in transit
        with self._fast_lock:
            entry = self._fast_rids.get(task_hex)
        if entry is not None:
            # fast-lane task: the C++ core drops it if still queued;
            # running → soft interrupt, or force → the lane worker
            # exits (surfacing as a crash, which a cancelled task maps
            # to TaskCancelledError — the classic force-kill contract).
            # The cancel goes to the CLIENT the task was submitted on:
            # after a lane death + reconnect, the new client's restarted
            # rid counter must never receive a stale rid.
            lane_client, rid = entry
            if not lane_client.dead:
                lane_client.cancel(rid, force=force)
            return True
        try:
            return self._call("cancel_task", task_id=task_hex,
                              force=force)["found"]
        except DaemonCrashed:
            return False

    # -- PG 2PC -----------------------------------------------------------
    def prepare_bundle(self, pg_id: str, index: int,
                       resources: Dict[str, float]) -> bool:
        try:
            return self._call("prepare_bundle", pg_id=pg_id, index=index,
                              resources=resources)["ok"]
        except DaemonCrashed:
            return False

    def commit_bundle(self, pg_id: str, index: int) -> bool:
        try:
            return self._call("commit_bundle", pg_id=pg_id,
                              index=index)["ok"]
        except DaemonCrashed:
            return False

    def cancel_bundle(self, pg_id: str, index: int) -> None:
        try:
            self._call("cancel_bundle", pg_id=pg_id, index=index)
        except DaemonCrashed:
            pass

    # -- object plane -----------------------------------------------------
    def _release_shm_grant(self, oid: bytes, out: Dict[str, Any]) -> None:
        """Drop the ref a get_object shm reply granted us: slot grants
        release through the local mapping (one atomic, zero RPC); the
        legacy internal-ref grant — or a slot we failed to map — falls
        back to the release_object RPC."""
        slot = out.get("slot")
        if slot is not None:
            if self.arenas.ext_release(out["shm"], slot):
                return
            try:
                self.client.call("release_object", oid=oid, slot=slot,
                                 timeout=5.0)
            except rpc.RpcError:
                pass
            return
        try:
            self.client.call("release_object", oid=oid, timeout=5.0)
        except rpc.RpcError:
            pass

    def get_object_blob(self, oid: bytes) -> Optional[bytes]:
        # slot_ok: this client understands ext-slot grants (releases
        # through the mapping, or release_object{slot} on attach
        # failure) — daemons withhold slots from clients that don't
        out = self._call("get_object", oid=oid, prefer_shm=True,
                         slot_ok=True)
        if out.get("missing"):
            return None
        if "shm" in out and out.get("shm"):
            view = self.arenas.read(out["shm"], out["capacity"],
                                    out["off"], out["size"])
            try:
                if view is not None:
                    return bytes(view)  # copy out, then release the pin
                # attach failed: re-request as bytes
                out2 = self._call("get_object", oid=oid, prefer_shm=False)
                return None if out2.get("missing") else out2["blob"]
            finally:
                self._release_shm_grant(oid, out)
        return out["blob"]

    def get_object_view(self, oid: bytes, dtype, shape):
        """Zero-copy read-only numpy view of a RAW-tier arena entry on
        the same host: the daemon grants a shared-slot ref, we map the
        range with np.frombuffer, and a finalizer drops the ref — no
        payload bytes cross any wire, no serialization at all. None →
        caller takes the blob path (remote host, attach failure, or a
        daemon without the slot protocol)."""
        import numpy as np
        out = self._call("get_object", oid=oid, prefer_shm=True,
                         slot_ok=True)
        if out.get("missing") or not out.get("shm"):
            return None
        if out.get("slot") is None:
            self._release_shm_grant(oid, out)   # legacy internal ref
            return None
        handle = self.arenas.handle(out["shm"])
        if handle is None:
            self._release_shm_grant(oid, out)
            return None
        try:
            base = handle.view_range(out["off"], out["size"])
        except Exception:
            self._release_shm_grant(oid, out)   # never pin on failure
            return None
        import weakref
        # finalizer on the BASE frombuffer array: numpy collapses base
        # chains, so a slice of the reshaped result bases on `base` —
        # releasing on the derived array's death would drop the slot
        # ref while sub-views still map the bytes
        weakref.finalize(base, _ext_release_quiet, handle, out["slot"])
        arr = base.view(np.dtype(dtype))
        if shape is not None:
            arr = arr.reshape(tuple(shape))
        return arr

    def arena_reserve(self, key: bytes, size: int
                      ) -> Optional[Dict[str, Any]]:
        """Reserve arena space for a direct put; {off, arena} or None
        (no arena / full — caller falls back to the blob RPC)."""
        try:
            out = self._call("create_object", oid=key, size=size)
        except (DaemonCrashed, rpc.RemoteError):
            return None
        if not out.get("ok"):
            return None
        return out

    def arena_seal(self, key: bytes, ref: bytes, raw,
                   nbytes: int) -> bool:
        try:
            out = self._call("seal_object", oid=key, ref=ref,
                             raw=list(raw) if raw else None,
                             nbytes=nbytes)
        except (DaemonCrashed, rpc.RemoteError):
            return False
        return bool(out.get("ok"))

    def push_object(self, oid: bytes, to_addr,
                    ref: bytes = b"") -> Dict[str, Any]:
        """Proactive push of a local object to a peer daemon (sender
        side runs the PushManager: chunked, deduped, directory-aware)."""
        return self._call("push_object", oid=oid, to_addr=list(to_addr),
                          ref=ref)

    def put_object_blob(self, oid: bytes, blob: bytes) -> None:
        out = self._call("put_object", oid=oid, blob=blob)
        if isinstance(out, dict) and out.get("backpressure"):
            from ray_tpu.exceptions import MemoryPressureError
            raise MemoryPressureError(
                f"node {self.node_id.hex()[:8]} rejected put under "
                f"{out.get('level', 'hard')} memory pressure")

    def free_objects(self, oids: List[bytes]) -> None:
        try:
            self._call("free_objects", oids=oids)
        except DaemonCrashed:
            pass

    def queue_free(self, oid: bytes) -> None:
        """Zero-ref free: coalesced (time/size-bounded) instead of one
        single-element free_objects RPC per object."""
        if not self.dead:
            self._free.queue(oid)

    def flush_frees(self) -> None:
        """Drain the free buffer NOW (worker shutdown, node drain)."""
        if not self.dead:
            self._free.flush()

    def pull_object(self, oid: bytes,
                    from_addr: Optional[Tuple[str, int]] = None,
                    priority: int = 2) -> bool:
        """priority: 0=get, 1=wait, 2=task-args (pull_manager.h:38-51).
        ``from_addr=None`` resolves via the owner's object directory."""
        out = self._call("pull_object", oid=oid,
                         from_addr=list(from_addr) if from_addr else [],
                         priority=priority)
        return out.get("ok", False)

    # -- lifecycle --------------------------------------------------------
    def stop(self) -> None:
        self.flush_frees()      # no queued free may outlive the session
        try:
            if not self.dead:
                self.client.call("daemon_stop", timeout=2.0)
        except rpc.RpcError:
            pass
        self.mark_dead()
        if self.proc is not None:
            try:
                self.proc.wait(timeout=2.0)
            except subprocess.TimeoutExpired:
                self.proc.kill()

    def sigkill(self) -> None:
        """Chaos path: hard-kill the daemon process (node failure)."""
        if self.proc is not None:
            try:
                self.proc.kill()
            except OSError:
                pass
        self.mark_dead()

    def detach(self) -> None:
        """Disconnect from a daemon we did not spawn (joined cluster):
        close the connection, leave the process running."""
        self.flush_frees()      # the daemon lives on: release its store
        self.mark_dead()
        self.client.close()


def _ext_release_quiet(handle, slot: int) -> None:
    """Finalizer for zero-copy driver-side views: drop the shared-slot
    ref through the local mapping (must never raise)."""
    try:
        handle.ext_release(slot)
    except Exception:
        pass


def out_is_final(out) -> bool:
    return out is None or out.get("outcome") != "gen"


def _slim_spec_blob(spec) -> bytes:
    """Spec metadata without the live callable/args (the daemon runs no
    user code; payloads travel as fid + args blob)."""
    import copy

    slim = copy.copy(spec)
    slim.func = None
    slim.args = ()
    slim.kwargs = {}
    slim.scheduling_strategy = "DEFAULT"
    return cloudpickle.dumps(slim)


class RemoteActorInstance:
    """Driver-side handle to an actor hosted in a daemon's worker."""

    __slots__ = ("daemon", "actor_id", "fast_tag")

    def __init__(self, daemon: DaemonHandle, actor_id,
                 fast_tag: Optional[int] = None):
        self.daemon = daemon
        self.actor_id = actor_id
        # targeted fast-lane address of the actor's dedicated worker
        # (None: classic RPC path only)
        self.fast_tag = fast_tag

    def call_actor_method(self, spec, args_blob: bytes):
        """Same (kind, value) contract as DaemonHandle's classic path;
        plain calls ride the targeted lane (per-actor FIFO in the
        native core), streaming/runtime-env calls stay classic."""
        if (self.fast_tag is not None
                and spec.num_returns not in ("streaming", "dynamic")
                and not spec.runtime_env):
            fl = self.daemon._fast_client()
            if fl is not None:
                out = self.daemon._call_actor_fast(fl, self.fast_tag,
                                                   spec, args_blob)
                if out is not None:
                    return out
        return self.daemon.call_actor_method(spec, args_blob)


class RemoteStore:
    """Store facade for a RemoteNode: values live in the daemon's object
    table; the driver keeps a metadata mirror (key, size, tier, raw
    dtype/shape) and fetches on demand. Same-host paths are zero-copy:
    large puts reserve + mmap-write + seal arena space (the payload
    never rides an RPC frame), and RAW-tier gets return read-only
    ``np.frombuffer`` views pinned by shared-slot refs."""

    def __init__(self, daemon: DaemonHandle):
        from ray_tpu.objectplane.tiers import TierAccounting
        self.daemon = daemon
        # ObjectID -> (key, nbytes, tier, raw|None)
        self._meta: Dict[Any, tuple] = {}  #: guarded by self._lock
        self._lock = tracked_lock("cluster.remote_store", reentrant=False)
        # UNCHAINED ledger: this store is a metadata MIRROR — the bytes
        # live in the daemon's arena, and the daemon already publishes
        # that occupancy to the gauge each heartbeat. Chaining here
        # would double-count every daemon-held object in federated sums.
        self.tiers = TierAccounting()
        self.stats = {"gets": 0, "puts": 0, "direct_puts": 0,
                      "zero_copy_gets": 0}

    def register_remote(self, object_id, daemon_key: bytes,
                        nbytes: int, raw=None,
                        tier: Optional[str] = None) -> None:
        from ray_tpu.objectplane.tiers import TIER_HOST
        tier = tier or TIER_HOST
        raw = tuple(raw) if raw else None
        with self._lock:
            prev = self._meta.get(object_id)
            self._meta[object_id] = (daemon_key, nbytes, tier, raw)
        if prev is None:
            self.tiers.add(tier, nbytes)

    def put(self, object_id, value, nbytes: int = 0) -> None:
        key = b"put:" + object_id.binary()
        if self._direct_put_raw(object_id, key, value):
            return
        from ray_tpu._private.device_objects import wire_dumps
        blob = wire_dumps(value)
        if self._direct_put_blob(object_id, key, blob):
            return
        from ray_tpu._private.retry import RetryPolicy
        from ray_tpu.exceptions import MemoryPressureError
        # HARD-pressure backpressure is retriable by contract: the node
        # is actively spilling/preempting its way back to capacity, so
        # ride the policy until relief instead of failing the put
        RetryPolicy.default(deadline_s=30.0).run(
            lambda: self.daemon.put_object_blob(key, blob),
            loop="put.backpressure", retry_on=(MemoryPressureError,))
        self.register_remote(object_id, key, len(blob))
        self.stats["puts"] += 1

    # -- direct put (same-host zero-RPC-payload path) --------------------
    def _direct_put_raw(self, object_id, key: bytes, value) -> bool:
        """Large contiguous numpy arrays store as RAW arena bytes: the
        payload is written through the driver's own mapping and
        consumers (driver or attached workers) frombuffer it back with
        zero serialization."""
        if not getattr(self.daemon, "objectplane", False):
            return False
        from ray_tpu.objectplane.tiers import raw_put_eligible
        raw = raw_put_eligible(value)
        if raw is None:
            return False
        return self._arena_put(object_id, key,
                               memoryview(value).cast("B"), raw)

    def _direct_put_blob(self, object_id, key: bytes,
                         blob: bytes) -> bool:
        """Large pickled payloads still skip the RPC frame: the blob is
        mmap-written in place; only reserve+seal metadata travels."""
        if not getattr(self.daemon, "objectplane", False):
            return False
        from ray_tpu._private.config import cfg
        if len(blob) < int(cfg().direct_put_min_bytes):
            return False
        return self._arena_put(object_id, key, blob, None)

    def _arena_put(self, object_id, key: bytes, payload, raw) -> bool:
        size = (payload.nbytes if isinstance(payload, memoryview)
                else len(payload))
        out = self.daemon.arena_reserve(key, size)
        if out is None:
            return False    # arena full / no native store: blob path
        if not self.daemon.arenas.write(out["arena"], out["off"],
                                        payload):
            # we cannot map the arena (different host / no native
            # build): stop attempting direct puts on this handle and
            # abort the reserve
            self.daemon.objectplane = False
            self.daemon.free_objects([key])
            return False
        if not self.daemon.arena_seal(key, object_id.binary(), raw,
                                      size):
            self.daemon.free_objects([key])
            return False
        self.register_remote(object_id, key, size, raw=raw)
        self.stats["puts"] += 1
        self.stats["direct_puts"] += 1
        return True

    def get(self, object_id):
        with self._lock:
            entry = self._meta.get(object_id)
        if entry is None:
            raise KeyError(object_id)
        key, nbytes, tier, raw = entry
        self.stats["gets"] += 1
        if raw is not None:
            # only attempt the view when the arena is actually mappable
            # (attach failures cache): a remote-host driver would
            # otherwise pay grant + release + re-request round trips
            # per get before reaching the blob path
            attachable = (self.daemon.arena_name is not None
                          and self.daemon.arenas.handle(
                              self.daemon.arena_name) is not None)
            arr = (self.daemon.get_object_view(key, raw[0], raw[1])
                   if attachable else None)
            if arr is not None:
                self.stats["zero_copy_gets"] += 1
                from ray_tpu.objectplane.tiers import count_zero_copy_get
                count_zero_copy_get()
                return arr
            # remote host / attach failure: raw bytes over RPC
            import numpy as np
            blob = self.daemon.get_object_blob(key)
            if blob is None:
                raise KeyError(object_id)
            return np.frombuffer(blob, dtype=np.dtype(raw[0])).reshape(
                tuple(raw[1]))
        blob = self.daemon.get_object_blob(key)
        if blob is None:
            raise KeyError(object_id)
        return cloudpickle.loads(blob)

    def contains(self, object_id) -> bool:
        with self._lock:
            return object_id in self._meta

    def delete(self, object_id) -> None:
        with self._lock:
            entry = self._meta.pop(object_id, None)
        if entry is None:
            return
        self.tiers.add(entry[2], -entry[1])
        if not self.daemon.dead:
            # coalesced: the zero-ref callback fires once per object,
            # but the wire sees size/time-bounded free_objects batches
            self.daemon.queue_free(entry[0])

    def object_ids(self):
        with self._lock:
            return list(self._meta)

    def nbytes_of(self, object_id) -> int:
        with self._lock:
            entry = self._meta.get(object_id)
        return entry[1] if entry else 0

    def meta_of(self, object_id) -> Tuple[bytes, int, Any]:
        """(daemon store key, nbytes, raw dtype/shape|None) — the handle
        a peer daemon needs to transfer this object directly (push
        prefetch / drain migration)."""
        with self._lock:
            key, nbytes, _tier, raw = self._meta[object_id]
        return key, nbytes, raw

    def has_daemon_key(self, daemon_key: bytes) -> bool:
        """Directory support: does this node hold the given store key?"""
        with self._lock:
            return any(e[0] == daemon_key for e in self._meta.values())

    def used_bytes(self) -> int:
        with self._lock:
            return sum(e[1] for e in self._meta.values())

    def tier_bytes(self) -> Dict[str, int]:
        """Occupancy by (host-shm | device-HBM | spilled) tier."""
        return self.tiers.snapshot()

    def close(self) -> None:
        with self._lock:
            self._meta.clear()
        self.tiers.clear()


class _OwnerHolder:
    """Pins refs created on behalf of daemon workers, keyed by borrower
    ("t:<task>" / "a:<actor>" — reference: per-task borrow tracking,
    ``reference_count.h:73``). Holds release when the borrowing task
    finishes or the actor dies, NOT only on daemon disconnect — a
    long-lived daemon must not pin dead tasks' objects."""

    def __init__(self):
        self._held: Dict[Any, List[Any]] = {}  #: guarded by self._lock
        self._lock = tracked_lock("cluster.owner_holder", reentrant=False)

    def _hold(self, task_rid, obj) -> None:
        with self._lock:
            self._held.setdefault(task_rid or "_", []).append(obj)

    def release(self, key: str) -> None:
        """Drop one borrower's holds (the dropped ObjectRefs' __del__
        cascades into refcounting — outside the lock)."""
        # GIL-atomic emptiness probe: a stale non-empty read just takes
        # the lock; a stale empty read means the hold landed after this
        # release began — the same outcome as losing the lock race.
        if not self._held:      # raylint: disable=guarded-by
            return  # empty table: the common per-task case pays no lock
        with self._lock:
            dropped = self._held.pop(key, None)
        del dropped

    def clear(self) -> None:
        with self._lock:
            held, self._held = self._held, {}
        del held

    def num_keys(self) -> int:
        with self._lock:
            return len(self._held)


class OwnerService:
    """The driver's RPC server for daemon-initiated core operations
    (CoreWorkerService direction, ``core_worker.proto:457-577``)."""

    def __init__(self, runtime):
        self.runtime = runtime
        self.holder = _OwnerHolder()

    def handle_core_op(self, conn, rid, msg):
        def run():
            from ray_tpu._private.worker_process import dispatch_core_op

            try:
                from ray_tpu._private.device_objects import wire_dumps
                kw = cloudpickle.loads(msg["payload"])
                value = dispatch_core_op(self.runtime, self.holder,
                                         msg["call"], kw, msg.get("task"))
                conn.reply(rid, ok=True, value=wire_dumps(value))
            except BaseException as e:  # noqa: BLE001 — shipped back
                try:
                    blob = cloudpickle.dumps(e)
                except Exception:
                    blob = cloudpickle.dumps(RuntimeError(repr(e)))
                conn.reply(rid, ok=False, value=blob)

        threading.Thread(target=run, daemon=True,
                         name="owner-core-op").start()
        return HOLD


class ClusterBackend:
    """Spawns + tracks the head and daemon processes for one driver.

    Head fault tolerance: the head persists KV/pubsub to sqlite in the
    session dir; a supervisor thread here respawns a crashed head on the
    SAME port with the same state file, daemons re-register themselves
    (daemon.py grace loop), and the driver's HeadClient re-dials — so a
    head SIGKILL is a blip, not a lost cluster (reference:
    ``gcs/store_client/redis_store_client.h`` + raylet resync).
    """

    HEAD_RECONNECT_S = 20.0

    def __init__(self, runtime, num_daemons: int,
                 resources_per_daemon: Dict[str, float],
                 object_store_bytes: int = 256 * 1024 * 1024):
        import tempfile
        object_store_bytes = max(object_store_bytes, 1 << 20)
        self.runtime = runtime
        self.arenas = ArenaCache()
        self._owns_cluster = True   # we spawned head+daemons; we stop them
        self.node_resources: Dict[NodeID, Dict[str, float]] = {}
        self.session_dir = tempfile.mkdtemp(prefix="ray_tpu_session_")
        self._head_state = os.path.join(self.session_dir, "head_state.db")
        self.head_proc, self._head_port = _spawn(
            "ray_tpu._private.head", ["--state-path", self._head_state])
        self.head = HeadClient(("127.0.0.1", self._head_port),
                               reconnect_window=self.HEAD_RECONNECT_S)
        self._shutting_down = False
        self._supervisor = threading.Thread(
            target=self._supervise_head, daemon=True, name="head-supervisor")
        self._supervisor.start()
        self.owner_service = OwnerService(runtime)
        self.owner_server = rpc.serve(self.owner_service).start()
        self.daemons: Dict[NodeID, DaemonHandle] = {}  #: guarded by self._lock
        self._lock = tracked_lock("cluster.backend.daemons",
                                  reentrant=False)
        import json

        head_port = self._head_port
        for _ in range(num_daemons):
            node_id = NodeID.from_random()
            proc, port = _spawn("ray_tpu._private.daemon", [
                "--head", f"127.0.0.1:{head_port}",
                "--node-id", node_id.hex(),
                "--resources", json.dumps(resources_per_daemon),
                "--object-store-bytes", str(object_store_bytes),
            ])
            handle = DaemonHandle(node_id, ("127.0.0.1", port), proc,
                                  self.arenas)
            handle.hello(self.owner_server.addr, runtime.job_id,
                         runtime.namespace)
            handle.on_actor_worker_died = self._make_actor_death_cb()
            with self._lock:
                self.daemons[node_id] = handle
        self.head.subscribe("node", self._on_node_event)
        self.start_resource_reporter()
        self.start_task_event_flusher()

    @classmethod
    def attach(cls, runtime, address: str) -> "ClusterBackend":
        """Join an EXISTING cluster (`ray-tpu start`) as a new driver:
        connect to its head, discover registered daemons, and speak the
        same wire protocol — nothing is spawned and shutdown() leaves the
        cluster running (reference: a second driver connecting to a
        `ray start` cluster, scripts.py:676)."""
        import tempfile

        self = cls.__new__(cls)
        self.runtime = runtime
        self.arenas = ArenaCache()
        self._owns_cluster = False
        self.node_resources = {}
        self.session_dir = tempfile.mkdtemp(prefix="ray_tpu_driver_")
        self._head_state = None
        host, port = address.rsplit(":", 1)
        self._head_port = int(port)
        self.head_proc = None
        self.head = HeadClient((host, self._head_port),
                               reconnect_window=cls.HEAD_RECONNECT_S)
        self._shutting_down = False
        self.owner_service = OwnerService(runtime)
        self.owner_server = rpc.serve(self.owner_service).start()
        # single-threaded construction: attach() is a constructor, the
        # reporter/subscriber threads that contend start below
        self.daemons = {}       # raylint: disable=guarded-by
        self._lock = tracked_lock("cluster.backend.daemons",
                                  reentrant=False)
        for info in self.head.list_nodes():
            if not info["alive"]:
                continue
            self._join_node(info, add_runtime_node=False)
        if not self.daemons:    # raylint: disable=guarded-by
            raise RuntimeError(
                f"cluster at {address} has no alive nodes to join")
        self.head.subscribe("node", self._on_node_event)
        self.start_resource_reporter()
        self.start_task_event_flusher()
        return self

    def describe_peers(self) -> List[str]:
        """One line per connected daemon for debug_state dumps: which
        control-plane core the peer advertised in hello (the async_core
        capability bit), plus liveness. Mixed clusters — a rolling
        restart flipping ``async_core``, or an old daemon behind a new
        driver — are invisible on the wire (frames are byte-identical),
        so this is the one place an operator can SEE the mix."""
        out = []
        with self._lock:
            handles = list(self.daemons.values())
        for h in handles:
            core = "async" if h._async_core_remote else "threaded"
            out.append(f"daemon {h.node_id.hex()[:8]}: core={core} "
                       f"alive={not h.dead}")
        return out

    def start_resource_reporter(self, interval_s: float = 0.5) -> None:
        """Syncer gossip (``ray_syncer.h:83`` role): the driver is the
        scheduling authority, so it owns the true availability view —
        push it to the head periodically (and only when changed) for the
        state API / autoscaler / other drivers."""
        def loop():
            last: Dict[str, Any] = {}
            last_sent = 0.0
            while not self._shutting_down:
                time.sleep(interval_s)
                loads: Dict[str, Dict[str, float]] = {}
                with self._lock:
                    node_ids = list(self.daemons)
                for node_id in node_ids:
                    node = self.runtime.get_node(node_id)
                    if node is None or not node.alive:
                        continue
                    loads[node_id.hex()] = dict(node.ledger.available())
                # Re-send unchanged views inside the head's gossip
                # freshness window (2s): steady load must not age out
                # and let static heartbeat values take the view back.
                now = time.monotonic()
                if loads and (loads != last or now - last_sent > 1.5):
                    try:
                        self.head.report_resources(loads)
                    except rpc.RpcError:
                        continue  # lost report: retry next tick
                    last = loads  # only after a successful send
                    last_sent = now
                # fair-share federation rides the same tick: dirty
                # quota records to the head (persisted) + capable
                # daemons, and the throttled per-job usage report
                ten = getattr(self.runtime, "tenancy", None)
                if ten is not None and ten.enabled:
                    try:
                        ten.maybe_sync(self)
                    except Exception:
                        pass  # dirty records retry next tick

        threading.Thread(target=loop, daemon=True,
                         name="resource-reporter").start()

    def start_task_event_flusher(self, interval_s: float = 1.0) -> None:
        """Periodically ship NEW driver task events to the head's
        task-event store so state/timeline queries survive driver exit
        (reference: task_event_buffer.cc -> gcs_task_manager.h:94)."""
        self._task_event_cursor = 0
        flush_lock = threading.Lock()

        def flush_once() -> None:
            buf = getattr(self.runtime, "task_events", None)
            if buf is None:
                return
            # one flusher at a time: the periodic thread, shutdown's
            # final flush, and direct test calls share the cursor — a
            # concurrent read-push-advance would double-store the batch
            # (the head has no dedupe)
            with flush_lock:
                batch = buf.events_after(self._task_event_cursor)
                if not batch:
                    return
                job_hex = self.runtime.job_id.hex()
                for ev in batch:
                    ev.setdefault("job_id", job_hex)
                if _fp.ENABLED:
                    try:
                        # drop/error arm = flush lost in transit; the
                        # un-advanced cursor re-sends next interval
                        if _fp.fire("trace.flush",
                                    n=len(batch)) is _fp.DROP:
                            return
                    except Exception:
                        return
                try:
                    self.head.task_events_push(batch)
                except rpc.RpcError:
                    return   # lost flush: retry with same cursor
                self._task_event_cursor = batch[-1]["seq"]

        self._flush_task_events = flush_once

        def loop():
            while not self._shutting_down:
                time.sleep(interval_s)
                flush_once()

        threading.Thread(target=loop, daemon=True,
                         name="task-event-flusher").start()

    def _supervise_head(self) -> None:
        """Respawn a crashed head on the same port with the same state."""
        while not self._shutting_down:
            time.sleep(0.25)
            if self._shutting_down or self.head_proc.poll() is None:
                continue
            if _fp.ENABLED:
                try:
                    # delay arm extends the outage window; ANY error
                    # arm simulates a failed respawn attempt (next
                    # tick retries, like a lingering TIME_WAIT port) —
                    # an escape here would kill the supervisor thread
                    # and permanently disable head respawn
                    _fp.fire("head.respawn")
                except Exception:  # noqa: BLE001 — injected faults
                    continue
            try:
                proc, _ = _spawn(
                    "ray_tpu._private.head",
                    ["--state-path", self._head_state,
                     "--port", str(self._head_port)])
            except (RuntimeError, OSError):
                continue  # port may linger in TIME_WAIT; retry
            if self._shutting_down:
                # shutdown() won the race while we were spawning: don't
                # leak a fresh head that nothing will ever terminate
                proc.kill()
                return
            self.head_proc = proc

    def _make_actor_death_cb(self):
        def cb(actor_id_hex: str, cause: str) -> None:
            from ray_tpu._private.ids import ActorID

            try:
                self.runtime.on_actor_worker_died(
                    ActorID.from_hex(actor_id_hex), cause)
            except Exception:
                pass

        return cb

    def _join_node(self, info: Dict[str, Any],
                   add_runtime_node: bool) -> Optional[DaemonHandle]:
        """ONE node-join sequence, shared by attach() (initial sweep)
        and the mid-session 'added' event (autoscaler provisioning,
        `ray-tpu up` extension): connect, hello, wire callbacks, and
        replay driver-wide settings the daemon missed (memory limit)."""
        try:
            node_id = NodeID.from_hex(info["node_id"])
        except (KeyError, ValueError):
            return None
        with self._lock:
            if node_id in self.daemons or self._shutting_down:
                existing = self.daemons.get(node_id)
                if existing is not None:
                    # re-registered daemon (healed partition / head
                    # restart): adopt the head-minted epoch so stale
                    # frames still queued on the OLD connection are
                    # fenced, not double-observed
                    ep = int(info.get("epoch") or 0)
                    if ep > existing.epoch:
                        existing.epoch = ep
                return None
        try:
            handle = DaemonHandle(node_id, tuple(info["addr"]), None,
                                  self.arenas)
            handle.hello(self.owner_server.addr, self.runtime.job_id,
                         self.runtime.namespace)
        except (OSError, rpc.RpcError, DaemonCrashed, KeyError):
            return None    # raced its death; the death event follows
        handle.on_actor_worker_died = self._make_actor_death_cb()
        with self._lock:
            if node_id in self.daemons:         # concurrent add race
                handle.detach()
                return None
            self.daemons[node_id] = handle
        self.node_resources[node_id] = dict(info["resources"])
        # a limit set BEFORE this node joined must police it too
        mon = getattr(self.runtime, "memory_monitor", None)
        if mon is not None and getattr(mon, "_explicit_limit", None):
            try:
                handle.client.call("set_memory_limit",
                                   limit=mon._explicit_limit,
                                   timeout=5.0)
            except Exception:
                pass
        if add_runtime_node:
            node = self.runtime.add_remote_node(handle,
                                                dict(info["resources"]))
            if info.get("draining"):
                # joined mid-drain (e.g. we subscribed after the drain
                # event): start migration with the remaining window
                self.runtime.begin_node_drain(
                    node, float(info.get("drain_deadline_s") or 0.0),
                    info.get("drain_reason") or "drain")
            # joined while the node was already pressured (we missed
            # the node_pressure push): the gossip row carries the level
            level = (info.get("gossip_load") or {}).get("pressure")
            if level and level != "ok":
                handle._on_node_pressure(level)
        return handle

    def _on_node_event(self, event: Dict[str, Any]) -> None:
        kind = event.get("kind")
        if kind == "added":
            self._join_node(event.get("node") or {},
                            add_runtime_node=True)
            return
        if kind == "drain":
            # Graceful drain announced (self-announced preemption, or
            # another driver / the CLI): start proactive migration.
            # begin_node_drain is idempotent, so the initiating driver's
            # own direct call and this event coexist.
            try:
                node = self.runtime.get_node(
                    NodeID.from_hex(event["node_id"]))
            except (KeyError, ValueError):
                return
            if node is not None:
                self.runtime.begin_node_drain(
                    node, float(event.get("deadline_s") or 0.0),
                    event.get("reason") or "drain")
            return
        if kind != "death":
            return
        node_id = NodeID.from_hex(event["node_id"])
        with self._lock:
            handle = self.daemons.get(node_id)
        if handle is None:
            return
        # Do NOT skip when handle.dead is already set: an in-flight RPC
        # failure marks the handle dead without running the node-death
        # flow, and losing that race must not lose the actor restarts —
        # remove_node below is a no-op if the runtime already removed it.
        handle.mark_dead()
        # Route through the runtime's node-death flow (lost objects,
        # task retries, actor restarts).
        node = self.runtime.get_node(node_id)
        if node is not None:
            if event.get("drain_expired"):
                # the HEAD's deadline escalation beat the driver's own
                # timer (exactly-once accounting lives in the runtime)
                self.runtime.count_drain_escalation(node)
            try:
                self.runtime.remove_node(node, _from_cluster=True)
            except Exception:
                pass

    def report_daemon_dead(self, handle: DaemonHandle, reason: str) -> None:
        handle.mark_dead()
        try:
            self.head.mark_node_dead(handle.node_id.hex(), reason)
        except rpc.RpcError:
            pass

    def shutdown(self) -> None:
        # final task-event flush: post-mortem queries against a shared
        # (persistent) head see the driver's full history
        flush = getattr(self, "_flush_task_events", None)
        if flush is not None:
            try:
                flush()
            except Exception:
                pass
        self._shutting_down = True
        with self._lock:
            daemons = list(self.daemons.values())
            self.daemons.clear()
        for handle in daemons:
            if self._owns_cluster:
                handle.stop()
            else:       # joined cluster: just disconnect, don't kill
                handle.detach()
        if self._owns_cluster:
            try:
                self.head.stop_head()
            except Exception:
                pass
        self.head.close()
        if self.head_proc is not None and self._owns_cluster:
            try:
                self.head_proc.wait(timeout=2.0)
            except subprocess.TimeoutExpired:
                self.head_proc.kill()
        self.owner_server.stop()
        self.arenas.close()
        import shutil

        shutil.rmtree(self.session_dir, ignore_errors=True)
