"""Process workers: the default execution path for tasks and actors.

Reference capability (NOT a port): the raylet worker pool + core-worker
execution plane — workers are real OS processes
(``src/ray/raylet/worker_pool.h`` StartWorkerProcess/PopWorker/prestart),
every task payload crosses a serialization boundary
(``python/ray/_private/serialization.py``), functions are shipped through
a function table (``python/ray/_private/function_manager.py:196,265``),
and workers reach back into the cluster for nested operations
(``CoreWorkerService`` RPCs, ``protobuf/core_worker.proto:457-577``).

TPU-first placement rule: work that touches the accelerator (declares TPU
resources, or consumes device-tier ``jax.Array`` arguments) runs in the
mesh-owning process — one process owns the chip/mesh and XLA releases the
GIL, so in-process threads are the right execution vehicle for SPMD work.
Everything else (the control/data plane) runs in spawned worker processes
pinned to the host CPU platform.

Architecture (single host; the pipe is the wire):

  host Runtime ── WorkerClient ──(mp.Pipe, cloudpickle frames)── worker
    - ProcessRouter: eligibility + routing + pool mgmt + crash handling
    - WorkerClient: one live worker process; demux reader thread routes
      task results/yields and services worker-initiated "core" ops
      (get/put/submit/wait/actor calls) against the host Runtime
    - worker process: reader loop + per-task threads; a
      WorkerProxyRuntime is installed as the global runtime so the full
      public API (ray_tpu.get/put/remote/actors/generators) works inside
      tasks transparently.

Process actors: the actor instance lives in a dedicated worker process;
host-side the existing ActorExecutor machinery (ordering, concurrency
groups, restarts) drives a proxy instance whose method stubs RPC into the
process. A dead worker process surfaces as actor death → the normal
restart path replays the creation spec.
"""

from __future__ import annotations

import hashlib
import inspect
import itertools
import os
import queue
import sys
import threading
import time
import traceback
import weakref
from typing import Any, Dict, List, Optional, Tuple

import cloudpickle

from ray_tpu._private.ids import ActorID, TaskID
from ray_tpu._private.task_spec import TaskKind, TaskSpec


class WorkerCrashed(Exception):
    """The worker process died while something was running on it."""


# object-plane ops served by the worker's HOST daemon itself (never
# forwarded to the owner): zero-copy meta resolution + direct-put
# reserve/seal (docs/object_plane.md)
_SHM_LOCAL_OPS = frozenset({"shm_get_meta", "shm_release",
                            "shm_put_reserve", "shm_put_seal",
                            "shm_put_abort"})


# ---------------------------------------------------------------------------
# function table (code shipping)
# ---------------------------------------------------------------------------

_FN_TABLE: "Dict[str, bytes]" = {}
_FN_REFS: Dict[str, int] = {}
_FN_TABLE_LOCK = threading.Lock()
_FN_MEMO: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
# Blobs from unweakrefable callables can't be finalizer-evicted; cap how
# many zero-ref entries may accumulate before oldest-first eviction.
_FN_TABLE_SOFT_CAP = 2048


def _release_fn_blob(fid: str) -> None:
    """weakref.finalize callback: the last live callable for this blob was
    collected — nothing can resubmit it, so the table entry is dead weight
    (retries hold the spec's live func and re-export on submission)."""
    with _FN_TABLE_LOCK:
        n = _FN_REFS.get(fid, 0) - 1
        if n <= 0:
            _FN_REFS.pop(fid, None)
            _FN_TABLE.pop(fid, None)
        else:
            _FN_REFS[fid] = n


def export_function(fn) -> Tuple[str, bytes]:
    """Serialize ``fn`` once and register it in the function table;
    returns (function_id, blob). Workers fetch the blob by id on first
    use and cache it (reference: function_manager.py export/fetch)."""
    try:
        cached = _FN_MEMO.get(fn)
    except TypeError:
        cached = None
    if cached is not None:
        return cached
    blob = cloudpickle.dumps(fn)
    fid = hashlib.sha1(blob).hexdigest()
    entry = (fid, blob)
    with _FN_TABLE_LOCK:
        _FN_TABLE[fid] = blob
        if len(_FN_TABLE) > _FN_TABLE_SOFT_CAP:
            # evict oldest zero-ref blobs (insertion-ordered dict)
            for old_fid in [f for f in _FN_TABLE
                            if _FN_REFS.get(f, 0) <= 0]:
                if len(_FN_TABLE) <= _FN_TABLE_SOFT_CAP:
                    break
                if old_fid != fid:
                    _FN_TABLE.pop(old_fid, None)
    try:
        _FN_MEMO[fn] = entry
        with _FN_TABLE_LOCK:
            _FN_REFS[fid] = _FN_REFS.get(fid, 0) + 1
        weakref.finalize(fn, _release_fn_blob, fid)
    except TypeError:
        pass  # unweakrefable callables just re-serialize
    return entry


def _local_fn_blob(msg) -> Optional[bytes]:
    """The blob for a worker fetch_function core op, if this process's
    own table has it (payload is the pickled kw dict)."""
    try:
        kw = cloudpickle.loads(msg["payload"])
        with _FN_TABLE_LOCK:
            return _FN_TABLE.get(kw.get("fid"))
    except Exception:
        return None


def register_function_blob(blob: bytes) -> str:
    """Register an ALREADY-pickled callable (e.g. fetched from the head
    KV by the cross-language tier) so pool workers can fetch it by id."""
    fid = hashlib.sha1(blob).hexdigest()
    with _FN_TABLE_LOCK:
        _FN_TABLE[fid] = blob
        _FN_REFS[fid] = _FN_REFS.get(fid, 0) + 1
    return fid


def fetch_function_blob(fid: str) -> bytes:
    with _FN_TABLE_LOCK:
        blob = _FN_TABLE.get(fid)
    if blob is None:
        raise KeyError(f"function {fid} not in function table")
    return blob


# ---------------------------------------------------------------------------
# worker-process side
# ---------------------------------------------------------------------------

_current_rid = threading.local()


def _borrower_key() -> Optional[str]:
    """Owner-side borrow key for refs created on this caller's behalf:
    actor context → held for the actor's lifetime; task context → held
    until the owner finishes that task (reference: per-task borrows,
    ``reference_count.h:73``)."""
    try:
        from ray_tpu._private import runtime_context
        ctx = runtime_context._ctx.get()
    except Exception:
        return None
    if ctx is None:
        return None
    if ctx.actor_id is not None:
        return "a:" + ctx.actor_id.hex()
    if ctx.task_id is not None:
        return "t:" + ctx.task_id.hex()
    return None


def _dump_exc(e: BaseException) -> bytes:
    tb = traceback.format_exc()
    try:
        return cloudpickle.dumps((e, tb))
    except Exception:
        return cloudpickle.dumps(
            (RuntimeError(f"{type(e).__name__}: {e}"), tb))


def _safe_dumps(value: Any) -> bytes:
    from ray_tpu._private.device_objects import wire_dumps
    return wire_dumps(value)   # sharding-preserving jax wire format


class _GeneratorStateProxy:
    """Worker-side view of a host GeneratorState (ObjectRefGenerator)."""

    def __init__(self, state: "_WorkerState", task_id: TaskID):
        self._state = state
        self._task_id = task_id

    def next_ref(self, index: int, timeout: Optional[float] = None):
        out = self._state.call_host("gen_next", task_id=self._task_id,
                                    index=index, timeout=timeout)
        if out is None:
            raise StopIteration
        return out

    @property
    def finished(self) -> bool:
        return self._state.call_host("gen_finished", task_id=self._task_id)


class _GcsProxy:
    def __init__(self, state: "_WorkerState"):
        self._state = state

    def get_actor_info(self, actor_id):
        return self._state.call_host("gcs_get_actor_info",
                                     actor_id=actor_id)

    def get_named_actor(self, name, namespace):
        return self._state.call_host("gcs_get_named_actor", name=name,
                                     namespace=namespace)

    # internal KV (debugger session registry, collectives, ...)
    def kv_put(self, key, value, overwrite=True, namespace=b""):
        return self._state.call_host("gcs_kv_put", key=key, value=value,
                                     overwrite=overwrite,
                                     namespace=namespace)

    def kv_get(self, key, namespace=b""):
        return self._state.call_host("gcs_kv_get", key=key,
                                     namespace=namespace)

    def kv_del(self, key, namespace=b""):
        return self._state.call_host("gcs_kv_del", key=key,
                                     namespace=namespace)

    def kv_keys(self, prefix=b"", namespace=b""):
        return self._state.call_host("gcs_kv_keys", prefix=prefix,
                                     namespace=namespace)


class _PgManagerProxy:
    """Worker-side pg_manager facade: returns a picklable clone of the
    host's PlacementGroup (handle semantics — id/bundles/state)."""

    def __init__(self, state: "_WorkerState"):
        self._state = state

    def get(self, pg_id):
        return self._state.call_host("pg_get", pg_id=pg_id)

    def create(self, bundles, strategy, name=""):
        return self._state.call_host("pg_create", bundles=bundles,
                                     strategy=strategy, name=name)

    def remove(self, pg):
        return self._state.call_host("pg_remove", pg_id=pg.id)

    def table(self):
        return self._state.call_host("pg_table")

    def ready_ref(self, pg_id):
        return self._state.call_host("pg_ready_ref", pg_id=pg_id)


class _NoopRefcounter:
    """Worker-held refs are kept alive host-side per task/actor (the host
    pins every ref a worker creates until the task — or the actor — ends),
    so worker-local counting is intentionally a no-op."""

    def add_local_ref(self, oid):
        pass

    def remove_local_ref(self, oid):
        pass


class WorkerProxyRuntime:
    """Installed as the global runtime inside a worker process: forwards
    the core API to the host over the pipe. Duck-types the Runtime surface
    that ObjectRef / RemoteFunction / ActorHandle / the module-level API
    touch."""

    def __init__(self, state: "_WorkerState"):
        self._state = state
        self.refcounter = _NoopRefcounter()
        self.gcs = _GcsProxy(state)
        self.pg_manager = _PgManagerProxy(state)
        self._actor_lock = threading.RLock()
        self._actor_executors: Dict[ActorID, Any] = {}

    # Pooled workers serve different runtimes over their lifetime, so
    # job/namespace are fetched from the currently-bound host.
    @property
    def namespace(self):
        return self._state.call_host("host_info")["namespace"]

    @property
    def job_id(self):
        return self._state.call_host("host_info")["job_id"]

    # -- objects ---------------------------------------------------------
    def get(self, refs, timeout: Optional[float] = None):
        refs = list(refs)
        out = self._shm_get(refs, timeout)
        if out is not None:
            return out
        return self._state.call_host("get", refs=refs,
                                     timeout=timeout)

    def _shm_get(self, refs, timeout: Optional[float]):
        """Zero-copy resolve through the attached node arena: (offset,
        nbytes) metadata from the daemon, ``np.frombuffer`` on the
        mapping — no payload crosses the pipe and raw-tier arrays skip
        serialization entirely. Per-object slot refs (taken daemon-side
        on our behalf) keep every view safe from LRU eviction until
        released. Returns None to take the classic owner path (arena
        absent/failed, or the host predates the protocol)."""
        try:
            from ray_tpu.objectplane import arena as _oparena
            ar = _oparena.get_arena()
            if ar is None or not refs or ar.store() is None:
                return None
            metas = self._state.call_host(
                "shm_get_meta", oids=[r.id.binary() for r in refs])
        except Exception:
            return None
        if not isinstance(metas, list) or len(metas) != len(refs):
            return None
        values = [None] * len(refs)
        missing: List[int] = []
        pending = {i: m for i, m in enumerate(metas)
                   if isinstance(m, dict)}
        try:
            for i, meta in enumerate(metas):
                if not isinstance(meta, dict):
                    missing.append(i)
                    continue
                # ownership handoff BEFORE resolving: from here this
                # slot's single release belongs to the code below (view
                # finalizer, or the loads finally) — the except sweep
                # must never release it a second time, or a concurrent
                # reader's ref would be consumed and eviction could
                # unmap bytes it still views
                del pending[i]
                raw = meta.get("raw")
                if raw:
                    values[i] = ar.view(meta["off"], meta["size"],
                                        meta["slot"], dtype=raw[0],
                                        shape=raw[1])
                else:
                    store = ar.store()
                    view = store.view_range(meta["off"], meta["size"])
                    try:
                        values[i] = cloudpickle.loads(memoryview(view))
                    finally:
                        ar.release_slot(meta["slot"])
        except Exception:
            # mid-resolve failure: drop every granted-but-unconsumed
            # slot ref and fall back wholesale (slots already handed
            # off released above or via their view finalizers)
            for meta in pending.values():
                try:
                    ar.release_slot(meta["slot"])
                except Exception:
                    pass
            return None
        if missing:
            fetched = self._state.call_host(
                "get", refs=[refs[i] for i in missing], timeout=timeout)
            for i, v in zip(missing, fetched):
                values[i] = v
        return values

    def put(self, value, _owner_pin: bool = False):
        if not _owner_pin:
            ref = self._shm_put(value)
            if ref is not None:
                return ref
        return self._state.call_host("put", value=value)

    def _shm_put(self, value):
        """Direct put: reserve arena space through the daemon, write
        the payload IN PLACE through our own mapping, and send only the
        seal message — the payload never rides the pipe or an RPC
        frame. Returns the owner-registered ObjectRef, or None to take
        the classic path (small value, no arena, any failure)."""
        try:
            from ray_tpu.objectplane import arena as _oparena
            ar = _oparena.get_arena()
            if ar is None or ar.store() is None:
                return None
            from ray_tpu._private.object_store import _is_device_value
            if _is_device_value(value):
                return None     # device tier stays owner-managed
            from ray_tpu._private.config import cfg
            min_direct = int(cfg().direct_put_min_bytes)
            from ray_tpu.objectplane.tiers import raw_put_eligible
            raw = raw_put_eligible(value)
            if raw is not None:
                payload = memoryview(value).cast("B")
                nbytes = payload.nbytes
            else:
                from ray_tpu._private.worker import _find_nested_refs
                if _find_nested_refs(value):
                    # nested ObjectRefs need the owner's borrowed-ref
                    # registration (classic put path) — a sealed blob
                    # would hold refs the refcounter can't see
                    return None
                blob = _safe_dumps(value)
                if len(blob) < min_direct:
                    return None
                payload = blob
                nbytes = len(blob)
            node_hex = self._node_hex()
            if node_hex is None:
                return None     # no task context: owner path
            from ray_tpu._private.ids import ObjectID
            oid = ObjectID.from_random()
            key = b"wput:" + oid.binary()
            out = self._state.call_host("shm_put_reserve", key=key,
                                        size=nbytes)
            if not isinstance(out, dict) or "off" not in out:
                return None     # arena full: classic path spills/inlines
        except Exception:
            return None
        try:
            ar.write(out["off"], payload)
        except Exception:
            # the reserve succeeded but the write didn't (mapping
            # detached mid-flight): drop the reservation or its
            # creator-ref'd bytes would leak for the arena's lifetime
            self._shm_put_abort(key)
            return None
        if not self._seal_with_retry(key, oid, raw, nbytes):
            self._shm_put_abort(key)
            return None
        try:
            return self._state.call_host(
                "put_stored", oid=oid.binary(), key=key, nbytes=nbytes,
                raw=raw, node=node_hex)
        except Exception:
            self._shm_put_abort(key)
            return None

    def _seal_with_retry(self, key: bytes, oid, raw,
                         nbytes: int) -> bool:
        from ray_tpu._private import failpoints as _fp
        for _ in range(3):
            if _fp.ENABLED:
                try:
                    # drop arm = the seal message is lost in transit;
                    # resend — sealing is idempotent at the daemon
                    if _fp.fire("shm.seal", nbytes=nbytes) is _fp.DROP:
                        continue
                except Exception:
                    continue
            try:
                out = self._state.call_host(
                    "shm_put_seal", key=key, ref=oid.binary(), raw=raw)
            except Exception:
                return False
            return bool(isinstance(out, dict) and out.get("ok"))
        return False

    def _shm_put_abort(self, key: bytes) -> None:
        try:
            self._state.call_host("shm_put_abort", key=key)
        except Exception:
            pass

    @staticmethod
    def _node_hex() -> Optional[str]:
        try:
            from ray_tpu._private import runtime_context
            ctx = runtime_context._ctx.get()
            nid = getattr(ctx, "node_id", None) if ctx else None
            return nid.hex() if nid is not None else None
        except Exception:
            return None

    def wait(self, refs, num_returns: int = 1,
             timeout: Optional[float] = None, fetch_local: bool = True):
        return self._state.call_host("wait", refs=list(refs),
                                     num_returns=num_returns,
                                     timeout=timeout,
                                     fetch_local=fetch_local)

    # -- tasks / actors --------------------------------------------------
    def submit_task(self, spec: TaskSpec, record_lineage: bool = True):
        return self._state.call_host("submit_task", spec=spec)

    def create_actor(self, spec: TaskSpec, get_if_exists: bool = False):
        return self._state.call_host("create_actor", spec=spec,
                                     get_if_exists=get_if_exists)

    def kill_actor(self, actor_id, no_restart: bool = True,
                   cause: str = "ray_tpu.kill() called"):
        return self._state.call_host("kill_actor", actor_id=actor_id,
                                     no_restart=no_restart, cause=cause)

    def cancel(self, ref, force: bool = False, recursive: bool = True):
        return self._state.call_host("cancel", ref=ref, force=force,
                                     recursive=recursive)

    def generator_state(self, task_id: TaskID) -> _GeneratorStateProxy:
        return _GeneratorStateProxy(self._state, task_id)

    # -- cluster introspection -------------------------------------------
    def cluster_resources(self):
        return self._state.call_host("cluster_resources")

    def available_resources(self):
        return self._state.call_host("available_resources")


class _WorkerState:
    def __init__(self, conn, boot: Dict[str, Any]):
        self.conn = conn
        self.boot = boot
        self.namespace = boot.get("namespace", "default")
        self.job_id = boot.get("job_id")
        arena = boot.get("arena")
        if arena:
            # the daemon's worker hello hands us its arena (name,
            # capacity): attach lazily on first object-plane use
            try:
                from ray_tpu.objectplane import arena as _oparena
                _oparena.configure(arena[0], arena[1])
            except Exception:
                pass    # plane unavailable: classic RPC path
        self._send_lock = threading.Lock()
        self._ids = itertools.count()
        self._pending: Dict[str, list] = {}  #: guarded by self._pending_lock
        self._pending_lock = threading.Lock()
        self._task_threads: Dict[str, threading.Thread] = {}
        self.actor_instance: Any = None
        # serializes actor-method execution between the classic mp
        # channel (streaming calls) and the targeted fast lane; only
        # engaged once the lane binds (_lane_bound) so non-lane actors
        # keep their configured concurrency semantics
        self.actor_lock = threading.RLock()
        self._lane_bound = False
        self._fn_cache: Dict[str, Any] = {}
        self._gen_sems: Dict[str, threading.Semaphore] = {}
        self.proxy = WorkerProxyRuntime(self)
        # compiled-DAG channel loop (dag_start/dag_stop ops)
        self._dag_stop: Any = None
        self._dag_thread: Any = None
        self._dag_channels: Dict[str, Any] = {}
        self._dag_gen: Any = None

    def send(self, msg: Dict[str, Any]) -> None:
        blob = cloudpickle.dumps(msg)
        with self._send_lock:
            self.conn.send_bytes(blob)

    def call_host(self, call: str, **kw) -> Any:
        rid = f"w{next(self._ids)}"
        ev = threading.Event()
        slot = [ev, True, None]
        with self._pending_lock:
            self._pending[rid] = slot
        from ray_tpu._private.device_objects import wire_dumps
        self.send({"op": "core", "id": rid, "call": call,
                   "task": getattr(_current_rid, "rid", None),
                   # globally-unique borrower key (reference: per-task
                   # borrow tracking, reference_count.h:73) — the worker
                   # rid above is only unique per process, so the
                   # owner's cross-daemon holder cannot key on it
                   "task_key": _borrower_key(),
                   "payload": wire_dumps(kw)})   # device args preserved
        ev.wait()
        if slot[1]:
            return slot[2]
        raise slot[2]


    # -- main loop -------------------------------------------------------
    def serve_forever(self) -> None:
        while True:
            try:
                msg = cloudpickle.loads(self.conn.recv_bytes())
            except (EOFError, OSError, ConnectionResetError):
                os._exit(0)
            op = msg.get("op")
            if op == "shutdown":
                os._exit(0)
            elif op == "reply":
                with self._pending_lock:
                    slot = self._pending.pop(msg["for"], None)
                if slot is not None:
                    slot[1] = msg["ok"]
                    slot[2] = cloudpickle.loads(msg["value"])
                    slot[0].set()
            elif op in ("execute_task", "create_actor", "call_method",
                        "reset_actor", "dag_start", "dag_stop"):
                t = threading.Thread(target=self._handle, args=(msg,),
                                     daemon=True,
                                     name=f"task-{msg['id']}")
                self._task_threads[msg["id"]] = t
                t.start()
            elif op == "gen_ack":
                sem = self._gen_sems.get(msg["target"])
                if sem is not None:
                    sem.release()
            elif op == "cancel":
                self._async_raise(msg["target"])
            elif op == "extend_sys_path":
                import sys as _sys
                for p in msg.get("paths", []):
                    if p not in _sys.path:
                        _sys.path.append(p)
            elif op == "profile_burst":
                # on-demand stack sampling; a thread so the burst never
                # blocks the op loop (results keep flowing while it runs)
                def _burst(msg=msg):
                    try:
                        from ray_tpu.util import profiling as _prof
                        rec = _prof.burst_record(
                            f"worker:{os.getpid()}",
                            duration_s=float(msg.get("duration") or 2.0))
                        self.send({"id": msg["id"], "op": "result",
                                   "ok": True,
                                   "blob": cloudpickle.dumps(rec)})
                    except BaseException as e:  # noqa: BLE001 — shipped
                        self.send({"id": msg["id"], "op": "result",
                                   "ok": False, "blob": _dump_exc(e)})
                threading.Thread(target=_burst, daemon=True,
                                 name="profile-burst").start()
            elif op == "join_fast_lane":
                # dedicate this worker to the native daemon core's task
                # lane (fast_lane.py); the mp channel stays open for
                # host ops (fetch_function, nested core ops, metrics).
                # With a tag, this is the TARGETED (actor) lane.
                try:
                    from ray_tpu._private.fast_lane import (
                        worker_fast_lane_start)
                    worker_fast_lane_start(tuple(msg["addr"]), self,
                                           tag=msg.get("tag"))
                    if msg.get("tag") is not None:
                        self._lane_bound = True
                    self.send({"id": msg["id"], "op": "result",
                               "ok": True,
                               "blob": cloudpickle.dumps(None)})
                except BaseException as e:  # noqa: BLE001 — shipped
                    self.send({"id": msg["id"], "op": "result",
                               "ok": False, "blob": _dump_exc(e)})

    def _async_raise(self, rid: str) -> None:
        """Best-effort KeyboardInterrupt into the thread running ``rid``
        (reference: non-force ray.cancel interrupts the worker)."""
        import ctypes
        t = self._task_threads.get(rid)
        if t is None or not t.is_alive():
            return
        ctypes.pythonapi.PyThreadState_SetAsyncExc(
            ctypes.c_ulong(t.ident), ctypes.py_object(KeyboardInterrupt))

    def _resolve_runtime_env(self, renv):
        """pkg:// URIs -> node-local extracted dirs (fetched once from
        the owner through the host channel and cached)."""
        if not renv:
            return renv
        from ray_tpu._private import runtime_env_packaging as pkg

        def resolve(value):
            if not (isinstance(value, str)
                    and value.startswith(pkg.PKG_SCHEME)):
                return value
            local = pkg.cached_dir(value)
            if local is None:
                local = pkg.extract_blob(
                    value, self.call_host("fetch_runtime_pkg", uri=value))
            return local

        out = dict(renv)
        if out.get("working_dir"):
            out["working_dir"] = resolve(out["working_dir"])
        if out.get("py_modules"):
            out["py_modules"] = [resolve(m) for m in out["py_modules"]]
        return out

    # -- compiled-DAG channel loop ---------------------------------------
    # Reference capability: the accelerated-DAG per-actor execution loop
    # (`python/ray/dag/compiled_dag_node.py` _do_exec_tasks) — after one
    # dag_start RPC, every execute() flows ONLY through pre-allocated
    # shm channels; no task submission, no object store.
    def _dag_start(self, spec: Dict[str, Any]):
        from ray_tpu.dag.shm_channel import ShmChannel
        if self._dag_thread is not None:
            # superseded binding (an abandoned CompiledDAG that was
            # never torn down): stop the stale loop, serve the new one
            self._dag_teardown()
        channels = {name: ShmChannel(name=name)
                    for name in spec["channels"]}
        consts = spec["consts"]
        stages = spec["stages"]
        stop = threading.Event()

        def loop():
            import sys as _sys
            import traceback as _tb

            from ray_tpu.dag.shm_channel import ChannelClosed
            while not stop.is_set():
                try:
                    for st in stages:
                        self._dag_run_stage(st, channels, consts, stop)
                except ChannelClosed:
                    return
                except Exception:
                    # e.g. ChannelFull on an oversized stage output:
                    # the channel chain cannot carry this — at least
                    # leave a driver-visible diagnostic (worker logs
                    # forward to the driver) before the loop dies
                    print("[compiled-dag] worker loop died:\n"
                          + _tb.format_exc(), file=_sys.stderr,
                          flush=True)
                    return

        t = threading.Thread(target=loop, daemon=True, name="dag-loop")
        self._dag_stop = stop
        self._dag_thread = t
        self._dag_channels = channels
        self._dag_gen = spec.get("gen")
        t.start()
        return None

    def _dag_run_stage(self, st, channels, consts, stop) -> None:
        def fetch(src):
            kind, key = src
            if kind == "chan":
                # idle waiting has NO deadline: a compiled DAG parked
                # for hours must still answer the next execute(); the
                # stop event is the only exit
                return channels[key].read(stop=stop, timeout=None)
            return ("ok", consts[key])

        inputs = [fetch(s) for s in st["args"]]
        kw_in = {k: fetch(s) for k, s in st["kwargs"].items()}
        err = next((v for s, v in inputs if s != "ok"),
                   next((v for s, v in kw_in.values() if s != "ok"),
                        None))
        if err is not None:
            out = ("err", err)       # propagate upstream failure
        else:
            try:
                method = getattr(self.actor_instance, st["method"])
                out = ("ok", method(
                    *[v for _, v in inputs],
                    **{k: v for k, (_, v) in kw_in.items()}))
            except BaseException as e:  # noqa: BLE001 — via channel
                out = ("err", e)
        for name in st["out"]:
            channels[name].write(out[0], out[1], stop=stop,
                                 timeout=3600.0)

    def _dag_teardown(self):
        if self._dag_stop is not None:
            self._dag_stop.set()
        if self._dag_thread is not None:
            self._dag_thread.join(timeout=5)
        for ch in self._dag_channels.values():
            ch.close()
        self._dag_stop = None
        self._dag_thread = None
        self._dag_channels = {}
        self._dag_gen = None
        return None

    def _fn(self, msg: Dict[str, Any]):
        if "fn_blob" in msg:
            return cloudpickle.loads(msg["fn_blob"])
        fid = msg["fn_id"]
        fn = self._fn_cache.get(fid)
        if fn is None:
            fn = cloudpickle.loads(self.call_host("fetch_function",
                                                  fid=fid))
            self._fn_cache[fid] = fn
        return fn

    def _handle(self, msg: Dict[str, Any]) -> None:
        import contextlib

        from ray_tpu._private import runtime_context
        from ray_tpu.runtime_env import apply_runtime_env

        rid = msg["id"]
        _current_rid.rid = rid
        ctx = msg.get("ctx") or {}
        # exec-phase span: the user function body measured IN the worker
        # (the only process that can see it). It PIGGYBACKS on the result
        # frame — zero extra pipe writes/pickles on the hot path — and
        # the host ingests it into its span sink (daemon -> head via
        # heartbeat; driver -> its own task-event buffer).
        trace = (ctx.get("trace")
                 if msg["op"] in ("execute_task", "call_method") else None)
        t_exec0 = time.perf_counter() if trace else 0.0

        def exec_span():
            if not trace:
                return None
            from ray_tpu._private.events import wall_at
            nid = ctx.get("node_id")
            tid = ctx.get("task_id")
            end = time.perf_counter()
            return {
                "task_id": tid.hex() if tid is not None else "",
                "name": ctx.get("task_name", ""), "event": "SPAN",
                "phase": "exec",
                "node_id": nid.hex() if nid is not None else "",
                "proc": f"worker:{os.getpid()}",
                "trace_id": trace.get("id", ""),
                "wall_ts": wall_at(end), "start_wall": wall_at(t_exec0),
                "dur_s": end - t_exec0}

        try:
            token = runtime_context._set_context(**ctx)
            try:
                with apply_runtime_env(
                        self._resolve_runtime_env(msg.get("runtime_env"))), \
                        _post_mortem_on_error(), \
                        contextlib.ExitStack() as _alock:
                    if msg["op"] == "create_actor":
                        cls = self._fn(msg)
                        args, kwargs = cloudpickle.loads(msg["args_blob"])
                        self.actor_instance = cls(*args, **kwargs)
                        result = None
                    elif msg["op"] == "call_method":
                        method = getattr(self.actor_instance, msg["method"])
                        args, kwargs = cloudpickle.loads(msg["args_blob"])
                        if self._lane_bound:
                            # held through the STREAMING drain below
                            # too (the ExitStack closes after it): a
                            # lane call must not interleave with a
                            # classic streaming method's body on a
                            # serialized actor
                            _alock.enter_context(self.actor_lock)
                        result = method(*args, **kwargs)
                    elif msg["op"] == "dag_start":
                        result = self._dag_start(
                            cloudpickle.loads(msg["args_blob"]))
                    elif msg["op"] == "dag_stop":
                        gen = (cloudpickle.loads(msg["args_blob"])
                               if msg.get("args_blob") else None)
                        # generation-scoped: a STALE CompiledDAG being
                        # GC'd must not kill a newer binding's loop
                        if gen is None or gen == getattr(
                                self, "_dag_gen", None):
                            result = self._dag_teardown()
                        else:
                            result = None
                    elif msg["op"] == "reset_actor":
                        self._dag_teardown()   # recycle = no stale loop
                        # Clean actor teardown: drop the instance so the
                        # process can be recycled into the idle pool
                        # (spawns are expensive; prestart can't keep up
                        # on small hosts). If ANYTHING still references
                        # the instance after gc (a background thread the
                        # actor started, a module global, ...) the worker
                        # is dirty and must be killed, not recycled —
                        # report it so the host takes the kill path.
                        inst, self.actor_instance = self.actor_instance, None
                        wr = weakref.ref(inst) if inst is not None else None
                        del inst
                        import gc
                        gc.collect()
                        if wr is not None and wr() is not None:
                            raise RuntimeError("actor instance still "
                                               "referenced; worker dirty")
                        result = None
                    else:
                        fn = self._fn(msg)
                        args, kwargs = cloudpickle.loads(msg["args_blob"])
                        result = fn(*args, **kwargs)
                    if inspect.isgenerator(result):
                        # Producer-side flow control (reference:
                        # GeneratorBackpressureWaiter): at most
                        # `backpressure` unacked items cross the pipe;
                        # the host acks as the consumer pulls them.
                        bp = msg.get("backpressure") or -1
                        sem = None
                        if bp > 0:
                            sem = threading.Semaphore(bp)
                            self._gen_sems[rid] = sem
                        try:
                            self.send({"id": rid, "op": "gen_start"})
                            for item in result:
                                if sem is not None:
                                    sem.acquire()
                                self.send({"id": rid, "op": "yield",
                                           "blob": _safe_dumps(item)})
                            self._flush_metrics()   # before release
                            self.send({"id": rid, "op": "result",
                                       "ok": True,
                                       "span": exec_span(),  # drain incl.
                                       "blob": _safe_dumps(None)})
                        finally:
                            self._gen_sems.pop(rid, None)
                        return
            finally:
                runtime_context._reset_context(token)
            # flush BEFORE the result send: once the host sees the
            # result it may release (or kill) this worker, and a flush
            # in flight after that is lost
            self._flush_metrics()
            self.send({"id": rid, "op": "result", "ok": True,
                       "span": exec_span(),
                       "profile": _result_profile(),
                       "blob": _safe_dumps(result)})
        except BaseException as e:  # noqa: BLE001 — shipped to host
            try:
                self._flush_metrics()
                self.send({"id": rid, "op": "result", "ok": False,
                           "span": exec_span(),
                           "profile": _result_profile(),
                           "blob": _dump_exc(e)})
            except (BrokenPipeError, OSError):
                os._exit(1)
        finally:
            self._task_threads.pop(rid, None)

    def _flush_metrics(self) -> None:
        """User metrics created in THIS worker flow to the driver's
        Prometheus endpoint (reference: worker -> agent -> exporter)."""
        try:
            from ray_tpu.util import metrics as _metrics
            deltas = _metrics.drain_deltas()
            if deltas:
                self.call_host("metrics_push", entries=deltas)
        except Exception:
            pass


# Worker profile piggyback (the span discipline): the CUMULATIVE
# continuous-sampler record rides at most one result frame per second;
# the host ingests it into profiling's remote store and the daemon's
# heartbeat ships it to the head. None (the common case) costs one
# cloudpickle'd NoneType on the frame.
_PROFILE_RESULT_S = 1.0
_last_profile_sent = [0.0]


def _result_profile():
    try:
        from ray_tpu.util import profiling as _prof
        rec = _prof.process_profile()
        if rec is None:
            return None
        now = time.monotonic()
        if now - _last_profile_sent[0] < _PROFILE_RESULT_S:
            return None
        _last_profile_sent[0] = now
        return rec
    except Exception:
        return None


def _post_mortem_on_error():
    """Distributed debugger hook — single definition lives in
    ray_tpu.util.rpdb (shared with the in-process path); guarded so a
    debugger-side import failure never masks the user's exception."""
    import contextlib
    try:
        from ray_tpu.util.rpdb import post_mortem_on_error
        return post_mortem_on_error()
    except Exception:
        return contextlib.nullcontext()


def _child_main(conn) -> None:
    """Worker bootstrap, forked from the forkserver template process (NOT
    multiprocessing spawn — that re-imports the parent's __main__, which
    breaks under REPLs/stdin drivers and pulls arbitrary driver-side
    module state into every worker; and NOT a fresh ``python -c`` — that
    pays ~0.3s of interpreter+import startup per worker where a fork is
    ~10ms). The first frame on the pipe is the boot config."""
    import signal

    # Terminal Ctrl+C goes to the whole foreground process group; workers
    # must not die with it (the driver decides shutdown; force-cancel uses
    # SIGTERM). The old subprocess path got this from start_new_session.
    signal.signal(signal.SIGINT, signal.SIG_IGN)
    boot = cloudpickle.loads(conn.recv_bytes())
    os.environ.update(boot.get("env", {}))
    for _p in boot.get("extra_sys_path", []):
        if _p not in sys.path:
            sys.path.append(_p)
    if boot.get("log_dir"):
        # Per-worker log files + tail-to-driver (reference:
        # _private/log_monitor.py; VERDICT r2 #9).
        from ray_tpu._private.log_monitor import redirect_process_output
        try:
            redirect_process_output(boot["log_dir"])
        except OSError:
            pass
    if boot.get("force_cpu_platform"):
        # Env-level pinning only (no jax import): jax has NOT been
        # imported yet in this fresh process — worker startup must stay
        # cheap (importing jax costs ~1.7s) — so the env vars are
        # authoritative when user code first imports it.
        from ray_tpu._private.platform import pin_cpu_env
        pin_cpu_env(boot.get("cpu_devices"))
    from ray_tpu._private import worker as worker_mod

    # network-chaos role tag: any control-plane socket this worker opens
    # (e.g. fast-lane result delivery) matches worker>* link policies
    from ray_tpu._private import netchaos as _nc
    _nc.set_local_role("worker")

    # continuous profiler (profiling_hz via the env the host shipped in
    # boot["env"] / inherited from the forkserver template; default off)
    try:
        from ray_tpu.util import profiling as _prof
        _prof.maybe_start_from_config(f"worker:{os.getpid()}")
    except Exception:
        pass
    state = _WorkerState(conn, boot)
    worker_mod._global_runtime = state.proxy  # type: ignore[assignment]
    state.serve_forever()


# ---------------------------------------------------------------------------
# host side
# ---------------------------------------------------------------------------

class _Pending:
    __slots__ = ("q",)

    def __init__(self):
        self.q: "queue.Queue" = queue.Queue()


_DEAD = object()  # sentinel pushed into pending queues on worker death


_MP_CTX = None
_MP_CTX_LOCK = threading.Lock()


def _mp_context():
    """Forkserver context every worker forks from. The forkserver is the
    template process: it preloads this module (and the worker runtime) once,
    under a cleaned environment — workers never own the accelerator (router
    eligibility keeps TPU work in the mesh-owning host process), so the
    template must not run the TPU plugin's sitecustomize registration
    (~2s of startup + a tunnel the worker must not touch) and pins
    ``JAX_PLATFORMS=cpu`` for every descendant."""
    global _MP_CTX
    with _MP_CTX_LOCK:
        if _MP_CTX is not None:
            return _MP_CTX
        import multiprocessing as mp
        from multiprocessing import forkserver as _fs

        ctx = mp.get_context("forkserver")
        repo_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        # NOTE deliberately narrow: JAX_PLATFORMS is NOT touched here —
        # mutating it in the driver's global env, even briefly, races a
        # driver thread importing jax and could pin the HOST backend to
        # CPU. The template never imports jax (verified: the preloads
        # don't pull it when PALLAS_AXON_POOL_IPS is unset), and each
        # worker pins itself at boot via the boot frame.
        saved = {k: os.environ.get(k)
                 for k in ("PALLAS_AXON_POOL_IPS", "PYTHONPATH")}
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
        os.environ["PYTHONPATH"] = repo_root + (
            os.pathsep + saved["PYTHONPATH"] if saved["PYTHONPATH"] else "")
        try:
            # PRIVATE ForkServer instance: multiprocessing's module-level
            # singleton may already be running (started by user code) with
            # the wrong env and no preloads — and our template must never
            # serve user forks either. _start_sans_main swaps this
            # instance in around each of our Process.start() calls.
            global _OUR_FORKSERVER
            _OUR_FORKSERVER = _fs.ForkServer()
            # pyarrow MUST be imported on a template/main thread: this
            # image's libarrow ties allocator state to the importing
            # thread's TLS — first-import inside a short-lived task
            # thread, then use from another thread after it exits,
            # segfaults (verified: plain-process repro, no fork needed).
            # Preloading in the template also makes every forked worker
            # inherit warm imports for free.
            _OUR_FORKSERVER.set_forkserver_preload(
                ["ray_tpu._private.worker_process",
                 "ray_tpu._private.worker",
                 "pyarrow"])
            _OUR_FORKSERVER.ensure_running()
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        import atexit

        atexit.register(_shutdown_worker_plane)
        _MP_CTX = ctx
        return ctx


def _shutdown_worker_plane() -> None:
    """Interpreter-exit hook: kill idle pooled workers and release the
    private forkserver. Without this, worker/template processes keep the
    multiprocessing resource-tracker pipe open and the tracker's __del__
    during final GC blocks interpreter exit (observed with grpc loaded,
    whose import makes shutdown GC collect the tracker)."""
    _POOL_CLOSED.set()
    deadline = time.monotonic() + 3.0
    while _PRESTARTING[0] > 0 and time.monotonic() < deadline:
        time.sleep(0.02)   # let racing spawns land so drain catches them
    try:
        drain_pool()
    except Exception:
        pass
    fs = _OUR_FORKSERVER
    if fs is not None:
        try:
            fd = getattr(fs, "_forkserver_alive_fd", None)
            if fd is not None:
                os.close(fd)
                fs._forkserver_alive_fd = None
        except OSError:
            pass


_START_LOCK = threading.Lock()
_OUR_FORKSERVER = None


def _start_sans_main(p) -> None:
    """Start a worker Process on OUR forkserver, WITHOUT multiprocessing's
    main-module fixup.

    spawn.get_preparation_data() tells the child to re-run the driver's
    ``__main__`` (runpy.run_path) — a worker must never do that: it would
    re-execute arbitrary user scripts in every worker (the reference
    default_worker is likewise a clean entrypoint, never the user script;
    driver-side functions reach workers through the function table
    instead). Both monkeypatches are scoped: the lock serializes our
    starts, the spawn patch checks the starting thread's identity (a
    concurrent user Process.start() on another thread sees stock
    behavior), and the forkserver global is restored before release."""
    from multiprocessing import forkserver as _fs
    from multiprocessing import spawn as _spawn

    with _START_LOCK:
        orig = _spawn.get_preparation_data
        me = threading.get_ident()

        def sans_main(name):
            d = orig(name)
            if threading.get_ident() == me:
                d.pop("init_main_from_path", None)
                d.pop("init_main_from_name", None)
            return d

        # popen_forkserver calls the module-level alias (a bound method
        # of the import-time singleton), so that alias is what we swap.
        saved_connect = _fs.connect_to_new_process
        _spawn.get_preparation_data = sans_main
        if _OUR_FORKSERVER is not None:
            _fs.connect_to_new_process = _OUR_FORKSERVER.connect_to_new_process
        try:
            p.start()
        finally:
            _fs.connect_to_new_process = saved_connect
            _spawn.get_preparation_data = orig


class _ProcHandle:
    """subprocess.Popen-shaped facade over a multiprocessing.Process."""

    __slots__ = ("p",)

    def __init__(self, p):
        self.p = p

    @property
    def pid(self):
        return self.p.pid

    def poll(self):
        return None if self.p.is_alive() else self.p.exitcode

    def wait(self, timeout=None):
        self.p.join(timeout)
        if self.p.is_alive():
            import subprocess
            raise subprocess.TimeoutExpired("worker", timeout)
        return self.p.exitcode

    def terminate(self):
        try:
            self.p.terminate()
        except Exception:
            pass

    def kill(self):
        try:
            self.p.kill()
        except Exception:
            pass




def rebind_pg(rt, spec):
    """Specs built inside a worker carry a pickled PlacementGroup CLONE
    (stale bundles, no node assignments); re-bind the strategy to the host
    manager's live object by id."""
    strat = getattr(spec, "scheduling_strategy", None)
    pg = getattr(strat, "placement_group", None)
    if pg is not None:
        live = rt.pg_manager.get(pg.id)
        if live is not None:
            strat.placement_group = live
    return spec


def dispatch_core_op(rt, holder, call: str, kw: Dict[str, Any],
                     task_rid: Optional[str]) -> Any:
    """Owner-side dispatch of a worker/daemon-initiated core operation.

    Shared by the in-process WorkerClient pipe path and the cluster-mode
    owner RPC service (reference: CoreWorkerService,
    ``protobuf/core_worker.proto:457-577``). ``holder`` pins refs created
    on behalf of the remote caller via ``_hold(task_rid, obj)``.
    """
    if call == "get":
            return rt.get(kw["refs"], timeout=kw.get("timeout"))
    if call == "put":
        ref = rt.put(kw["value"])
        holder._hold(task_rid, ref)
        return ref
    if call == "put_stored":
        # direct-put registration: the worker already wrote + sealed
        # the payload in its node's arena; the owner only records
        # ownership, location, and (for raw tier) the array dtype/shape
        ref = rt.put_stored(kw["oid"], kw["key"], kw["nbytes"],
                            kw.get("raw"), kw["node"])
        holder._hold(task_rid, ref)
        return ref
    if call == "wait":
        return rt.wait(kw["refs"], num_returns=kw["num_returns"],
                       timeout=kw["timeout"],
                       fetch_local=kw["fetch_local"])
    if call == "submit_task":
        spec = rebind_pg(rt, kw["spec"])
        refs = rt.submit_task(spec)
        holder._hold(task_rid, refs)
        return refs
    if call == "create_actor":
        return rt.create_actor(rebind_pg(rt, kw["spec"]),
                               get_if_exists=kw["get_if_exists"])
    if call == "kill_actor":
        return rt.kill_actor(kw["actor_id"],
                             no_restart=kw["no_restart"],
                             cause=kw["cause"])
    if call == "cancel":
        return rt.cancel(kw["ref"], force=kw["force"],
                         recursive=kw["recursive"])
    if call == "gen_next":
        state = rt.generator_state(kw["task_id"])
        try:
            ref = state.next_ref(kw["index"], timeout=kw.get("timeout"))
            holder._hold(task_rid, ref)
            return ref
        except StopIteration:
            return None
    if call == "gen_finished":
        return rt.generator_state(kw["task_id"]).finished
    if call == "gcs_get_actor_info":
        return rt.gcs.get_actor_info(kw["actor_id"])
    if call == "gcs_get_named_actor":
        return rt.gcs.get_named_actor(kw["name"], kw["namespace"])
    if call.startswith("gcs_kv_"):
        # same store preference as ray_tpu.util.rpdb._kv: the head's KV
        # when one exists (cross-process discoverable), else local gcs
        backend = getattr(rt, "cluster_backend", None)
        store = getattr(backend, "head", None) or rt.gcs
        ns = kw.get("namespace", b"")
        if call == "gcs_kv_put":
            return store.kv_put(kw["key"], kw["value"],
                                overwrite=kw.get("overwrite", True),
                                namespace=ns)
        if call == "gcs_kv_get":
            return store.kv_get(kw["key"], namespace=ns)
        if call == "gcs_kv_del":
            return store.kv_del(kw["key"], namespace=ns)
        if call == "gcs_kv_keys":
            return store.kv_keys(kw["prefix"], namespace=ns)
    if call == "fetch_function":
        return fetch_function_blob(kw["fid"])
    if call == "metrics_push":
        from ray_tpu.util import metrics as _metrics
        _metrics.merge_deltas(kw["entries"])
        return True
    if call == "fetch_runtime_pkg":
        from ray_tpu._private.runtime_env_packaging import fetch_pkg_blob
        return fetch_pkg_blob(kw["uri"])
    if call == "locate_object":
        # Owner-keyed object directory (ownership_object_directory.h):
        # which daemons hold a copy of this object (by daemon store key),
        # answered from the owner's authoritative location metadata.
        key = kw["oid"]
        addrs = []
        with rt._nodes_lock:
            nodes = list(rt._nodes.values())
        for node in nodes:
            handle = getattr(node, "daemon", None)
            store = getattr(node, "store", None)
            has = getattr(store, "has_daemon_key", None)
            if (handle is not None and not handle.dead
                    and has is not None and has(key)):
                addrs.append(list(handle.addr))
        return addrs
    if call == "pg_get":
        return rt.pg_manager.get(kw["pg_id"])
    if call == "pg_create":
        return rt.pg_manager.create(kw["bundles"], kw["strategy"],
                                    kw["name"])
    if call == "pg_remove":
        pg = rt.pg_manager.get(kw["pg_id"])
        if pg is not None:
            rt.pg_manager.remove(pg)
        return None
    if call == "pg_table":
        return rt.pg_manager.table()
    if call == "pg_ready_ref":
        pg = rt.pg_manager.get(kw["pg_id"])
        if pg is None:
            raise ValueError("unknown placement group")
        ref = pg.ready()
        holder._hold(task_rid, ref)
        return ref
    if call == "host_info":
        return {"namespace": rt.namespace, "job_id": rt.job_id}
    if call == "cluster_resources":
        return rt.cluster_resources()
    if call == "available_resources":
        return rt.available_resources()
    raise ValueError(f"unknown core op {call!r}")


def _untrack_after(router, task_id, it):
    """Yield through a worker stream, untracking the task at stream end."""
    try:
        yield from it
    finally:
        router.untrack_task(task_id)


# Monotonic spawn counter: (pid, generation) identifies a worker to the
# object-plane grant ledger even if the OS recycles the pid within one
# daemon lifetime.
_WORKER_GEN = itertools.count(1)


class WorkerClient:
    """Host handle to one worker process."""

    def __init__(self, boot: Dict[str, Any]):
        ctx = _mp_context()
        self.conn, child = ctx.Pipe()
        p = ctx.Process(target=_child_main, args=(child,), daemon=True,
                        name="ray-tpu-worker")
        _start_sans_main(p)
        self.proc = _ProcHandle(p)
        self.gen = next(_WORKER_GEN)
        # set by the daemon at the worker's first arena grant; reclaim
        # keys the grant ledger off it when the process dies
        self.arena_client_id: Optional[str] = None
        child.close()
        # First frame: boot config (platform pinning etc.).
        self.conn.send_bytes(cloudpickle.dumps(boot))
        self._send_lock = threading.Lock()
        self._ids = itertools.count()
        self._pending: Dict[str, _Pending] = {}  #: guarded by self._pending_lock
        self._pending_lock = threading.Lock()
        # Objects created on behalf of the worker (refs from put/submit),
        # pinned until the creating task — or the whole actor — ends.
        self._holds: Dict[str, List[Any]] = {}
        self.runtime = None          # bound by the router on assignment
        self.node = None
        self.actor_id: Optional[ActorID] = None
        self.expected_death = False
        self.dead = False
        self.calls = 0
        self._on_death: List[Any] = []
        self._reader = threading.Thread(target=self._read_loop, daemon=True,
                                        name=f"wkr-read-{self.proc.pid}")
        self._reader.start()

    # -- plumbing --------------------------------------------------------
    def _send(self, msg: Dict[str, Any]) -> None:
        blob = cloudpickle.dumps(msg)
        try:
            with self._send_lock:
                self.conn.send_bytes(blob)
        except (BrokenPipeError, OSError):
            raise WorkerCrashed(
                f"worker {self.proc.pid} pipe closed "
                f"(exitcode={self.proc.poll()})")

    def _read_loop(self) -> None:
        while True:
            try:
                msg = cloudpickle.loads(self.conn.recv_bytes())
            except (EOFError, OSError, ConnectionResetError):
                self._on_dead()
                return
            except Exception:
                self._on_dead()
                return
            op = msg.get("op")
            if op in ("result", "gen_start", "yield"):
                if op == "result" and msg.get("span") is not None:
                    # exec-phase span piggybacked on the result frame:
                    # ingest into this host process's sink (daemon ->
                    # head via heartbeat; driver -> its own buffer)
                    try:
                        from ray_tpu._private import events as _events
                        _events.ingest_span_events(
                            getattr(self.runtime, "task_events", None),
                            [msg["span"]])
                    except Exception:
                        pass
                if op == "result" and msg.get("profile") is not None:
                    # worker profile piggyback (the span discipline):
                    # into this process's store; the daemon heartbeat
                    # (or a driver-side cluster_profile) federates it
                    try:
                        from ray_tpu.util import profiling as _prof
                        _prof.ingest_profile(msg["profile"])
                    except Exception:
                        pass
                with self._pending_lock:
                    pend = self._pending.get(msg["id"])
                if pend is not None:
                    pend.q.put(msg)
            elif op == "core":
                threading.Thread(target=self._serve_core, args=(msg,),
                                 daemon=True).start()

    def _on_dead(self) -> None:
        if self.dead:
            return
        self.dead = True
        with self._pending_lock:
            pending = list(self._pending.values())
        for p in pending:
            p.q.put(_DEAD)
        self._holds.clear()
        callbacks, self._on_death = self._on_death, []
        for cb in callbacks:
            try:
                cb(self)
            except Exception:
                pass

    def add_death_callback(self, cb) -> None:
        if self.dead:
            cb(self)
        else:
            self._on_death.append(cb)

    def alive(self) -> bool:
        return not self.dead and self.proc.poll() is None

    def notify_extend_sys_path(self, paths: List[str]) -> None:
        """Fire-and-forget: live workers learn new driver import roots
        (a late hello must also reach the prestarted pool)."""
        self._send({"op": "extend_sys_path", "paths": list(paths)})

    def kill(self, expected: bool = True) -> None:
        import subprocess
        _checkout_done(self)
        self.expected_death = self.expected_death or expected
        try:
            self._send({"op": "shutdown"})
        except WorkerCrashed:
            pass
        try:
            self.proc.wait(timeout=0.5)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            try:
                self.proc.wait(timeout=1.0)
            except subprocess.TimeoutExpired:
                pass
        try:
            self.conn.close()
        except OSError:
            pass

    # -- worker-initiated core ops --------------------------------------
    def _serve_core(self, msg: Dict[str, Any]) -> None:
        try:
            forward = getattr(self.runtime, "forward_core_op", None)
            shm = (getattr(self.runtime, "shm_ops", None)
                   if msg.get("call") in _SHM_LOCAL_OPS else None)
            local_fn = (_local_fn_blob(msg)
                        if (forward is not None
                            and msg.get("call") == "fetch_function")
                        else None)
            if shm is not None:
                # object-plane metadata ops are DAEMON-LOCAL: the whole
                # point is that neither metadata resolution nor payload
                # ever round-trips through the owner. The client handle
                # rides along so grants are charged to THIS worker's
                # (pid, generation) in the reclamation ledger.
                value = shm(msg["call"], cloudpickle.loads(msg["payload"]),
                            self)
                reply = {"op": "reply", "for": msg["id"], "ok": True,
                         "value": cloudpickle.dumps(value)}
            elif local_fn is not None:
                # function blobs are content-addressed (sha1 fid): serve
                # from this process's table when present — xlang fids
                # only exist here, and it skips a driver round trip
                reply = {"op": "reply", "for": msg["id"], "ok": True,
                         "value": cloudpickle.dumps(local_fn)}
            elif forward is not None:
                # Daemon mode: raw round-trip to the owner (driver); the
                # blob is already pickled at the owner's edge.
                ok, blob = forward(msg)
                reply = {"op": "reply", "for": msg["id"], "ok": ok,
                         "value": blob}
            else:
                value = self._core_dispatch(msg)
                reply = {"op": "reply", "for": msg["id"], "ok": True,
                         "value": _safe_dumps(value)}
        except BaseException as e:  # noqa: BLE001 — shipped back
            try:
                blob = cloudpickle.dumps(e)
            except Exception:
                blob = cloudpickle.dumps(RuntimeError(repr(e)))
            reply = {"op": "reply", "for": msg["id"], "ok": False,
                     "value": blob}
        try:
            self._send(reply)
        except WorkerCrashed:
            pass

    def _hold(self, task_rid: Optional[str], obj: Any) -> None:
        key = task_rid or "__actor__"
        if self.actor_id is not None:
            key = "__actor__"  # actor-held refs live as long as the actor
        self._holds.setdefault(key, []).append(obj)

    def _hold_key_for(self, msg: Dict[str, Any]) -> Optional[str]:
        """Classic workers key holds by worker rid (released in
        _finish); dedicated fast-lane workers have no per-call _finish,
        so their holds key by the global borrower id ('t:<task>') and
        release via ProcessRouter.release_borrows when the task ends."""
        if getattr(self, "fast_lane", False) and msg.get("task_key"):
            return msg["task_key"]
        return msg.get("task")

    def _core_dispatch(self, msg: Dict[str, Any]) -> Any:
        kw = cloudpickle.loads(msg["payload"])
        if msg["call"] == "metrics_push":
            # process-global registry, no runtime binding needed — the
            # post-task flush legitimately races release_worker()'s
            # runtime reset
            from ray_tpu.util import metrics as _metrics
            _metrics.merge_deltas(kw["entries"])
            return True
        rt = self.runtime
        if rt is None:
            raise RuntimeError("worker not bound to a runtime")
        return dispatch_core_op(rt, self, msg["call"], kw,
                                self._hold_key_for(msg))

    def _request(self, msg: Dict[str, Any]) -> Tuple[str, _Pending]:
        rid = f"h{next(self._ids)}"
        msg["id"] = rid
        pend = _Pending()
        with self._pending_lock:
            self._pending[rid] = pend
        if self.dead:
            pend.q.put(_DEAD)
            return rid, pend
        self._send(msg)
        return rid, pend

    def _finish(self, rid: str) -> None:
        with self._pending_lock:
            self._pending.pop(rid, None)
        self._holds.pop(rid, None)

    def profile_burst(self, duration: float = 2.0):
        """Sample this worker's stacks for ``duration`` seconds; returns
        the profile record, or None if the worker died mid-burst."""
        rid, pend = self._request({"op": "profile_burst",
                                   "duration": float(duration)})
        try:
            msg = pend.q.get(timeout=duration + 10.0)
        except queue.Empty:
            self._finish(rid)
            return None
        if msg is _DEAD:
            self._finish(rid)
            return None
        ok = msg.get("ok")
        blob = msg.get("blob")
        self._finish(rid)
        if not ok or blob is None:
            return None
        rec = cloudpickle.loads(blob)
        return rec if isinstance(rec, dict) else None

    # Daemons run no user code: with raw_outcomes they hand result blobs
    # through without unpickling (the owner deserializes at the edge).
    raw_outcomes = False

    def _wait_outcome(self, rid: str, pend: _Pending):
        """First message decides: value result, error, or generator."""
        msg = pend.q.get()
        if msg is _DEAD:
            self._finish(rid)
            raise WorkerCrashed(
                f"worker process {self.proc.pid} died "
                f"(exitcode={self.proc.poll()})")
        if msg["op"] == "gen_start":
            return ("gen", self._gen_iter(rid, pend))
        ok = msg["ok"]
        if self.raw_outcomes:
            self._finish(rid)
            return ("ok_raw" if ok else "err_raw", msg["blob"])
        payload = cloudpickle.loads(msg["blob"])
        self._finish(rid)
        if ok:
            return ("ok", payload)
        e, tb = payload
        setattr(e, "_remote_traceback", tb)
        return ("err", e)

    def _gen_iter(self, rid: str, pend: _Pending):
        try:
            while True:
                msg = pend.q.get()
                if msg is _DEAD:
                    raise WorkerCrashed(
                        f"worker process {self.proc.pid} died mid-stream")
                if msg["op"] == "yield":
                    if self.raw_outcomes:
                        # no ack here: in daemon mode the ack comes from
                        # the DRIVER's consumer via the gen_ack RPC, so
                        # flow control tracks end-consumption, not relay
                        yield ("yield_raw", msg["blob"])
                        continue
                    yield cloudpickle.loads(msg["blob"])
                    try:
                        # consumer pulled the item: grant the producer
                        # another flow-control token
                        self._send({"op": "gen_ack", "target": rid})
                    except WorkerCrashed:
                        pass
                    continue
                ok = msg["ok"]
                if self.raw_outcomes:
                    if not ok:
                        yield ("err_raw", msg["blob"])
                    return
                payload = cloudpickle.loads(msg["blob"])
                if not ok:
                    e, tb = payload
                    setattr(e, "_remote_traceback", tb)
                    raise e
                return
        finally:
            self._finish(rid)

    @staticmethod
    def _ctx_fields(spec: TaskSpec, node, runtime) -> Dict[str, Any]:
        return {
            "job_id": getattr(spec, "job_id", None) or runtime.job_id,
            "task_id": spec.task_id,
            "node_id": node.node_id if node is not None else None,
            "actor_id": spec.actor_id,
            "resources": spec.resources,
            "task_name": spec.name,
            "placement_group_id": spec.placement_group_id,
            "pg_capture": spec.pg_capture,
            "trace": ({"id": spec.trace_id}
                      if getattr(spec, "trace_sampled", False) else None),
        }

    def execute_task(self, spec: TaskSpec, node, fid: str,
                     args_blob: bytes):
        self.calls += 1
        rid, pend = self._request({
            "op": "execute_task", "fn_id": fid, "args_blob": args_blob,
            "ctx": self._ctx_fields(spec, node, self.runtime),
            "runtime_env": spec.runtime_env,
            "backpressure": spec.backpressure_num_objects,
        })
        router = self.runtime.process_router
        router.track_task(spec.task_id, self, rid)
        try:
            outcome = self._wait_outcome(rid, pend)
        except BaseException:
            router.untrack_task(spec.task_id)
            raise
        if outcome[0] == "gen":
            # Stay tracked while the worker streams — cancel()/crash
            # handling must be able to reach a producing generator task.
            return ("gen", _untrack_after(router, spec.task_id, outcome[1]))
        router.untrack_task(spec.task_id)
        return outcome

    def create_actor_instance(self, spec: TaskSpec, node, fid: str,
                              args_blob: bytes):
        self.calls += 1
        rid, pend = self._request({
            "op": "create_actor", "fn_id": fid, "args_blob": args_blob,
            "ctx": self._ctx_fields(spec, node, self.runtime),
            "runtime_env": spec.runtime_env,
        })
        return self._wait_outcome(rid, pend)

    def call_method(self, spec: TaskSpec, node, args_blob: bytes):
        self.calls += 1
        rid, pend = self._request({
            "op": "call_method", "method": spec.method_name,
            "args_blob": args_blob,
            "ctx": self._ctx_fields(spec, node, self.runtime),
            "runtime_env": spec.runtime_env,
        })
        return self._wait_outcome(rid, pend)

    def reset_actor(self):
        """Tear down the actor instance in-process (clean death path) so
        the worker can be recycled."""
        rid, pend = self._request({"op": "reset_actor", "ctx": {},
                                   "runtime_env": None})
        return self._wait_outcome(rid, pend)

    def cancel_request(self, rid: str) -> None:
        try:
            self._send({"op": "cancel", "target": rid})
        except WorkerCrashed:
            pass


# ---------------------------------------------------------------------------
# pool (module-level: idle workers survive runtime shutdown and are reused
# across test runtimes — reference: worker prestart/reuse across jobs)
# ---------------------------------------------------------------------------

_POOL_LOCK = threading.Lock()
_IDLE: List[WorkerClient] = []
# driver import roots shipped at hello (code-search-path role): new
# workers get them in the boot frame, live ones via an extend op
_EXTRA_SYS_PATH: List[str] = []
_SYS_PATH_VERSION = [0]
_ALL_WORKERS: "weakref.WeakSet" = weakref.WeakSet()


def set_extra_sys_path(paths: List[str]) -> None:
    changed = False
    for p in paths:
        if p not in _EXTRA_SYS_PATH:
            _EXTRA_SYS_PATH.append(p)
            changed = True
    if changed:
        _SYS_PATH_VERSION[0] += 1


# The hosting daemon's shm arena (name, capacity): handed to every
# worker in the boot frame so it can attach the segment and run the
# zero-copy object protocol. Unset outside daemon processes.
_ARENA_INFO: List[Optional[tuple]] = [None]


def set_arena_info(name: str, capacity: int) -> None:
    _ARENA_INFO[0] = (name, int(capacity))


def live_workers() -> List["WorkerClient"]:
    return [w for w in list(_ALL_WORKERS) if w.alive()]
_PRESTARTING = [0]
_POOL_CLOSED = threading.Event()   # interpreter exiting: no new spawns
# Demand tracking: the idle cap follows the high-water mark of concurrent
# checkouts (decayed on a window) so a burst of N parallel tasks keeps N
# workers warm instead of churning fork+join on every release (reference:
# worker_pool.h num_workers_soft_limit + idle reaping).
_ACTIVE = [0]
_PEAK = [0]
_PEAK_TS = [0.0]
_PEAK_WINDOW_S = 60.0
from ray_tpu._private.thread_pool import DaemonThreadPool

_REAPER = DaemonThreadPool(2, name="worker-reaper")


def _pool_floor() -> int:
    from ray_tpu._private.config import cfg
    n = cfg().process_pool_size
    return n if n > 0 else min(4, max(2, (os.cpu_count() or 4) // 2))


def _pool_target() -> int:
    """Idle cap: configured floor, raised to the recent peak of concurrent
    checkouts (bounded by process_pool_max)."""
    from ray_tpu._private.config import cfg
    return max(_pool_floor(), min(_PEAK[0], cfg().process_pool_max))


def _async_kill(w: WorkerClient) -> None:
    """Reap off the caller's thread: kill() blocks up to 1.5s on join."""
    _REAPER.submit(lambda: w.kill(expected=True))


def _make_boot() -> Dict[str, Any]:
    boot: Dict[str, Any] = {"env": {}}
    if _EXTRA_SYS_PATH:
        boot["extra_sys_path"] = list(_EXTRA_SYS_PATH)
    # Workers never own the accelerator: pin them to the CPU platform with
    # the same virtual device count the host uses (so jax-in-worker works
    # under the test mesh and cannot fight over the chip).
    boot["force_cpu_platform"] = True
    n = None
    try:
        import re
        m = re.search(r"--xla_force_host_platform_device_count=(\d+)",
                      os.environ.get("XLA_FLAGS", ""))
        if m:
            n = int(m.group(1))
    except Exception:
        pass
    boot["cpu_devices"] = n
    from ray_tpu._private.log_monitor import (log_to_driver_enabled,
                                              session_log_dir)
    boot["log_dir"] = (session_log_dir()
                       if log_to_driver_enabled() else None)
    if _ARENA_INFO[0] is not None:
        boot["arena"] = _ARENA_INFO[0]
    return boot


def _spawn_worker() -> WorkerClient:
    # version BEFORE building the boot: a set_extra_sys_path racing
    # this spawn makes the worker look stale, and ensure_sys_path
    # re-sends (idempotent) instead of silently missing the paths
    version = _SYS_PATH_VERSION[0]
    w = WorkerClient(_make_boot())
    w._sys_path_version = version
    _ALL_WORKERS.add(w)
    return w


def ensure_sys_path(w: "WorkerClient") -> None:
    """Re-send driver import roots if this worker predates the latest
    set_extra_sys_path (spawn/hello races leave stale workers)."""
    if getattr(w, "_sys_path_version", -1) != _SYS_PATH_VERSION[0]:
        try:
            w.notify_extend_sys_path(_EXTRA_SYS_PATH)
            w._sys_path_version = _SYS_PATH_VERSION[0]
        except Exception:
            pass


def _checkout_done(w: WorkerClient) -> None:
    """Decrement the active-checkout count exactly once per checkout;
    called from release_worker AND WorkerClient.kill so crash paths
    (which kill without releasing) keep the accounting straight."""
    with _POOL_LOCK:
        if getattr(w, "_checked_out", False):
            w._checked_out = False
            _ACTIVE[0] = max(0, _ACTIVE[0] - 1)


def acquire_worker() -> WorkerClient:
    got: Optional[WorkerClient] = None
    with _POOL_LOCK:
        now = time.monotonic()
        _ACTIVE[0] += 1
        if now - _PEAK_TS[0] > _PEAK_WINDOW_S:
            _PEAK[0] = _ACTIVE[0]
            _PEAK_TS[0] = now
        elif _ACTIVE[0] > _PEAK[0]:
            _PEAK[0] = _ACTIVE[0]
        while _IDLE:
            w = _IDLE.pop()
            if w.alive():
                got = w
                break
            _async_kill(w)
    if got is None:
        try:
            got = _spawn_worker()
        except BaseException:
            with _POOL_LOCK:   # keep _ACTIVE honest on spawn failure
                _ACTIVE[0] = max(0, _ACTIVE[0] - 1)
            raise
    got._checked_out = True
    ensure_sys_path(got)
    _maybe_prestart_async()
    return got


def release_worker(w: WorkerClient) -> None:
    _checkout_done(w)
    if w.actor_id is not None or not w.alive():
        _async_kill(w)
        return
    w.runtime = None
    w.node = None
    with _POOL_LOCK:
        if len(_IDLE) >= _pool_target():
            keep = False
        else:
            _IDLE.append(w)
            keep = True
    if not keep:
        _async_kill(w)


_FILL_RUNNING = [False]


def _maybe_prestart_async() -> None:
    """Keep the idle pool warm in the background (reference: PrestartWorkers).

    The deficit counts checked-out workers too: a burst's active workers
    return to the idle pool on release, so spawning replacements for them
    would overshoot and churn."""
    if _POOL_CLOSED.is_set():
        return
    with _POOL_LOCK:
        deficit = (_pool_target() - len(_IDLE) - _PRESTARTING[0]
                   - _ACTIVE[0])
        if deficit <= 0 or _FILL_RUNNING[0]:
            return
        _FILL_RUNNING[0] = True

    def fill():
        try:
            while not _POOL_CLOSED.is_set():
                with _POOL_LOCK:
                    deficit = (_pool_target() - len(_IDLE)
                               - _PRESTARTING[0] - _ACTIVE[0])
                    if deficit <= 0:
                        return
                    _PRESTARTING[0] += 1
                try:
                    w = _spawn_worker()
                finally:
                    with _POOL_LOCK:
                        _PRESTARTING[0] -= 1
                with _POOL_LOCK:
                    if (len(_IDLE) < _pool_target()
                            and not _POOL_CLOSED.is_set()):
                        _IDLE.append(w)
                    else:
                        _async_kill(w)
                        return
        except Exception:
            pass
        finally:
            with _POOL_LOCK:
                _FILL_RUNNING[0] = False
    threading.Thread(target=fill, daemon=True,
                     name="worker-prestart").start()


def drain_pool() -> None:
    """Kill every idle pooled worker (test hygiene / interpreter exit)."""
    with _POOL_LOCK:
        idle, _IDLE[:] = list(_IDLE), []
    for w in idle:
        w.kill()


# ---------------------------------------------------------------------------
# router (owned by the Runtime)
# ---------------------------------------------------------------------------

def _contains_device_value(value: Any) -> bool:
    from ray_tpu._private.object_store import _is_device_value
    return _is_device_value(value)


def _wants_accelerator(resources: Dict[str, float]) -> bool:
    return any(k == "TPU" or k.startswith("TPU") or k == "GPU"
               for k, v in (resources or {}).items() if v)


class _ProcessActorInstance:
    """Host-side proxy for an actor living in a worker process. The
    Runtime's actor-task executor detects this type and routes method
    calls through ProcessRouter.call_actor_method; all the host-side
    ActorExecutor machinery (ordering, concurrency groups, restarts)
    drives it exactly like a live instance."""

    __slots__ = ("_client", "_class_name")

    def __init__(self, client: WorkerClient, class_name: str):
        self._client = client
        self._class_name = class_name


class ProcessRouter:
    def __init__(self, runtime):
        self.runtime = runtime
        self.enabled = os.environ.get(
            "RAY_TPU_PROCESS_WORKERS", "1") != "0"
        self._actor_workers: Dict[ActorID, WorkerClient] = {}
        self._lock = threading.Lock()
        # task_id -> (client, rid) while a normal task runs in a process
        self._running: Dict[TaskID, Tuple[WorkerClient, str]] = {}
        # driver-local fast lane (the SAME native core the daemons run,
        # native/daemon_core.cc, hosted in THIS process): plain tasks
        # skip the per-task mp.Connection round trip and per-task
        # checkout entirely. Lazily started on first eligible task.
        self._fast = None                 # FastLaneClient
        self._fast_core = None            # CoreHandle
        self._fast_lock = threading.Lock()
        self._fast_workers: List[WorkerClient] = []
        # task hex -> (lane client, rid): the client pins the rid to
        # its generation (see cancel_task)
        self._fast_rids: Dict[str, Tuple[Any, int]] = {}
        self._fast_disabled = os.environ.get(
            "RAY_TPU_FAST_LANE", "1") == "0"
        self._fast_max = max(2, min(8, (os.cpu_count() or 4)))
        if self.enabled:
            # Launch the forkserver template synchronously during init()
            # (bounds the brief PALLAS_AXON_POOL_IPS env window to the
            # init call), then warm the pool in the background so the
            # first task/actor doesn't pay process-spawn latency
            # (reference: worker prestart, raylet/worker_pool.h).
            try:
                _mp_context()
            except Exception:
                pass
            _maybe_prestart_async()

    # -- eligibility -----------------------------------------------------
    def _serialize_payload(self, spec: TaskSpec, args, kwargs
                           ) -> Optional[Tuple[str, bytes]]:
        if _contains_device_value((args, kwargs)):
            return None
        try:
            fid, _ = export_function(spec.func)
            args_blob = cloudpickle.dumps((args, kwargs))
        except Exception:
            return None
        return fid, args_blob

    def eligible_task(self, spec: TaskSpec, args, kwargs):
        # pg_demand is the pre-rewrite demand snapshot: once a task is
        # scheduled into a placement group its resources are renamed to
        # bundle-scoped keys (_pg_<id>_<idx>_TPU) that plain name checks
        # would miss.
        demand = getattr(spec, "pg_demand", None) or spec.resources
        if (not self.enabled or spec.kind != TaskKind.NORMAL
                or getattr(spec, "in_process", False)
                or _wants_accelerator(demand)):
            return None
        return self._serialize_payload(spec, args, kwargs)

    def eligible_actor(self, spec: TaskSpec, args, kwargs):
        demand = getattr(spec, "pg_demand", None) or spec.resources
        if (not self.enabled or spec.kind != TaskKind.ACTOR_CREATION
                or getattr(spec, "in_process", False)
                or _wants_accelerator(demand)):
            return None
        cls = spec.func
        if not inspect.isclass(cls):
            return None
        from ray_tpu._private.worker import _class_is_async
        if _class_is_async(cls):
            return None  # asyncio actors run on the host loop
        return self._serialize_payload(spec, args, kwargs)

    # -- normal tasks ----------------------------------------------------
    def track_task(self, task_id: TaskID, client: WorkerClient,
                   rid: str) -> None:
        with self._lock:
            self._running[task_id] = (client, rid)

    def untrack_task(self, task_id: TaskID) -> None:
        with self._lock:
            self._running.pop(task_id, None)

    def worker_pid_for_task(self, task_id: TaskID) -> Optional[int]:
        """Test/chaos hook: pid of the process running a task."""
        with self._lock:
            entry = self._running.get(task_id)
        return entry[0].proc.pid if entry else None

    def execute_task(self, spec: TaskSpec, node, payload):
        fid, args_blob = payload
        if self._fast_eligible(spec):
            out = self._execute_fast(spec, node, fid, args_blob)
            if out is not None:
                return out
            # lane declined (down, or the function returned a live
            # generator): classic checkout below
        client = acquire_worker()
        client.runtime = self.runtime
        client.node = node
        try:
            outcome = client.execute_task(spec, node, fid, args_blob)
        except WorkerCrashed:
            client.kill(expected=False)
            raise
        if outcome[0] == "gen":
            # Streaming generator: the worker keeps producing after this
            # returns — release it only when the stream is drained, or
            # a full pool would kill the process mid-stream.
            return ("gen", self._release_after(client, outcome[1]))
        release_worker(client)
        return outcome

    # -- driver-local fast lane ------------------------------------------
    def _fast_eligible(self, spec: TaskSpec) -> bool:
        return (not self._fast_disabled
                and spec.num_returns == 1
                and not spec.runtime_env
                and not (spec.func is not None
                         and inspect.isgeneratorfunction(spec.func)))

    def _fast_client(self):
        if self._fast is not None and not self._fast.dead:
            return self._fast
        from ray_tpu._private.fast_lane import (CoreHandle,
                                                FastLaneClient,
                                                lane_reconnect_policy)
        try:
            with self._fast_lock:
                if self._fast is not None and not self._fast.dead:
                    return self._fast
                if self._fast_core is None:
                    core = CoreHandle()
                    if core.start("127.0.0.1", 0) is None:
                        self._fast_disabled = True   # no native build
                        return None
                    self._fast_core = core
                    threading.Thread(target=self._fast_pool_loop,
                                     daemon=True,
                                     name="router-fastlane").start()
                port = self._fast_core.port
            # connect OUTSIDE the lock: the retry window's backoff
            # sleeps must not stall cancel_task/_fast_rids bookkeeping
            from ray_tpu._private import failpoints as _fp

            def connect():
                if _fp.ENABLED:
                    _fp.fire("fast_lane.reconnect")
                return FastLaneClient(("127.0.0.1", port))

            fl = lane_reconnect_policy().run(
                connect, loop="fast_lane.reconnect",
                retry_on=(OSError, _fp.FailpointError))
            with self._fast_lock:
                if self._fast is None or self._fast.dead:
                    self._fast = fl
                else:
                    fl.close()      # lost the reconnect race
                return self._fast
        except Exception:
            self._fast_disabled = True
            return None

    def _fast_dedicate(self) -> WorkerClient:
        core = self._fast_core
        if core is None:
            raise RuntimeError("fast lane stopped")
        w = _spawn_worker()
        # NOT _checked_out: lane workers never enter the idle pool, and
        # marking them checked out would make their eventual kill()
        # decrement an _ACTIVE count they never incremented (skewing
        # pool sizing)
        w.fast_lane = True
        w.runtime = self.runtime
        w.node = None
        try:
            rid, pend = w._request({
                "op": "join_fast_lane",
                "addr": ["127.0.0.1", core.port]})
            out = w._wait_outcome(rid, pend)
            if out[0] not in ("ok", "ok_raw"):
                raise RuntimeError(f"fast-lane join failed: {out!r}")
        except BaseException:
            try:
                w.kill(expected=True)
            except Exception:
                pass
            raise
        ensure_sys_path(w)
        return w

    def _fast_pool_loop(self) -> None:
        """Queue-depth-driven sizing, like the daemon's lane pool. The
        whole maintenance step holds _fast_lock so shutdown()'s swap
        can never interleave with a dedicate (which would leak the
        just-spawned worker process)."""
        while not getattr(self.runtime, "_shutdown", False):
            try:
                with self._fast_lock:
                    if self._fast_core is None:
                        return        # shut down
                    alive = [w for w in self._fast_workers
                             if w.alive()]
                    self._fast_workers = alive
                    for w in alive:
                        ensure_sys_path(w)
                    stats = self._fast_core.stats()
                    if (not alive
                            or (stats.get("queued", 0) > 0
                                and len(alive) < self._fast_max)):
                        self._fast_workers.append(
                            self._fast_dedicate())
                        continue
            except Exception:
                time.sleep(1.0)
            time.sleep(0.25)

    def _execute_fast(self, spec: TaskSpec, node, fid: str,
                      args_blob: bytes):
        from ray_tpu._private import fast_lane as _fle
        fl = self._fast_client()
        if fl is None:
            return None
        payload = _fle.build_payload(
            spec, fid, args_blob,
            getattr(spec, "job_id", None) or self.runtime.job_id,
            node.node_id if node is not None else None)
        try:
            rid, slot = fl.submit(payload)
        except _fle.FastLaneError:
            return None                  # nothing submitted: classic
        task_hex = spec.task_id.hex()
        with self._fast_lock:
            # store the CLIENT with the rid: after a lane death +
            # reconnect the new client's rid counter restarts at 1, so
            # a bare rid could cancel an unrelated task on the new lane
            self._fast_rids[task_hex] = (fl, rid)
        try:
            kind, blob = fl.wait(slot)
        except _fle.FastLaneUnsubmitted:
            # frame never reached the wire (another submitter's flush
            # failed first): nothing ran — classic path, retry-free
            return None
        except _fle.FastLaneError as e:
            # submitted but the lane died: surface as a worker crash so
            # retry accounting applies (never a silent re-run)
            crash = WorkerCrashed(f"fast lane died mid-task: {e}")
            crash.fast_lane = True
            raise crash
        finally:
            with self._fast_lock:
                self._fast_rids.pop(task_hex, None)
        if kind == _fle.KIND_OK:
            return ("ok", cloudpickle.loads(blob))
        if kind == _fle.KIND_ERR:
            e, tb = cloudpickle.loads(blob)
            setattr(e, "_remote_traceback", tb)
            return ("err", e)
        if kind == _fle.KIND_GEN_LIST:
            # the function body already ran and the worker drained its
            # returned generator: replay as a real generator so the
            # streaming machinery engages without re-running the body
            return ("gen", _fle.replay_gen_list(blob))
        if kind == _fle.KIND_GEN_FALLBACK:
            return None     # legacy worker: stream via the classic path
        if kind == _fle.KIND_CANCELLED:
            return ("err", KeyboardInterrupt())
        if kind == _fle.KIND_CRASHED:
            crash = WorkerCrashed(blob.decode(errors="replace"))
            crash.fast_lane = True
            raise crash
        raise RuntimeError(f"unknown fast-lane outcome kind {kind}")

    def release_borrows(self, key: str) -> None:
        """Drop lane workers' owner-side holds for a finished borrower
        ('t:<task>' — per-task borrow release for the driver-local
        lane, mirroring the cluster OwnerHolder)."""
        if not self._fast_workers:
            return  # no driver-local lane: per-completion fast path
        for w in list(self._fast_workers):
            dropped = w._holds.pop(key, None)
            del dropped

    @staticmethod
    def _release_after(client: WorkerClient, it):
        try:
            yield from it
        finally:
            release_worker(client)

    def cancel_task(self, task_id: TaskID, force: bool) -> bool:
        task_hex = task_id.hex()
        with self._fast_lock:
            entry = self._fast_rids.get(task_hex)
        if entry is not None:
            # cancel on the client GENERATION the task was submitted on
            # — a reconnected lane restarts its rid counter, and a
            # stale rid sent there would kill an unrelated task
            lane_client, rid = entry
            if not lane_client.dead:
                lane_client.cancel(rid, force=force)
            return True
        with self._lock:
            entry = self._running.get(task_id)
        if entry is None:
            return False
        client, rid = entry
        if force:
            client.expected_death = False
            client.proc.terminate()  # surfaces as WorkerCrashed
        else:
            client.cancel_request(rid)
        return True

    # -- actors ----------------------------------------------------------
    def create_actor(self, spec: TaskSpec, node, payload):
        """Returns a _ProcessActorInstance, or raises the user's __init__
        exception / WorkerCrashed."""
        fid, args_blob = payload
        client = acquire_worker()
        client.runtime = self.runtime
        client.node = node
        client.actor_id = spec.actor_id
        try:
            kind, value = client.create_actor_instance(
                spec, node, fid, args_blob)
        except WorkerCrashed:
            client.kill(expected=False)
            raise
        if kind == "err":
            client.actor_id = None
            release_worker(client)  # init failed cleanly; process reusable
            raise value
        client.actor_since = time.time()
        # Actor ownership is a PERMANENT checkout: stop counting it in
        # _ACTIVE, or _PEAK could never decay below the live-actor count
        # and the idle pool would stay burst-sized forever.
        _checkout_done(client)
        with self._lock:
            self._actor_workers[spec.actor_id] = client
        actor_id = spec.actor_id
        client.add_death_callback(
            lambda c, aid=actor_id: self._actor_worker_died(aid, c))
        return _ProcessActorInstance(client, getattr(spec.func, "__name__",
                                                     "Actor"))

    def call_actor_method(self, instance: _ProcessActorInstance,
                          spec: TaskSpec, node, args, kwargs):
        client: WorkerClient = instance._client
        if client.dead:
            from ray_tpu import exceptions as exc
            raise exc.ActorDiedError(spec.actor_id,
                                     "actor worker process died")
        from ray_tpu._private.device_objects import wire_dumps
        args_blob = wire_dumps((args, kwargs))   # device args over wire
        try:
            return client.call_method(spec, node, args_blob)
        except WorkerCrashed as e:
            from ray_tpu import exceptions as exc
            raise exc.ActorDiedError(spec.actor_id, str(e))

    def _actor_worker_died(self, actor_id: ActorID,
                           client: WorkerClient) -> None:
        with self._lock:
            current = self._actor_workers.get(actor_id)
            if current is client:
                self._actor_workers.pop(actor_id, None)
        if client.expected_death:
            return
        rt = self.runtime
        if rt is None or getattr(rt, "_shutdown", False):
            return
        # Unexpected process death → actor death with restart semantics
        # (reference: GcsActorManager restart path on worker failure).
        try:
            rt.on_actor_worker_died(actor_id,
                                    f"actor worker process died "
                                    f"(pid {client.proc.pid})")
        except Exception:
            pass

    def discard_actor(self, actor_id: ActorID, expected: bool = True) -> None:
        with self._lock:
            client = self._actor_workers.pop(actor_id, None)
        if client is None:
            return
        with client._pending_lock:
            busy = bool(client._pending)
        if not expected or busy or not client.alive():
            # Unexpected death, or method calls still in flight (a killed
            # actor's process dies with its running work, reference
            # semantics; recycling a busy worker would let the pool-full
            # check kill it mid-call for an unrelated reason).
            client.kill(expected=expected)
            return
        # Clean death: reset the in-process instance and recycle the
        # worker into the idle pool instead of paying a respawn later.
        try:
            kind, _ = client.reset_actor()
        except Exception:
            kind = "err"
        if kind not in ("ok", "ok_raw"):
            client.kill(expected=True)
            return
        client._on_death.clear()  # stale actor-death callbacks
        client._holds.pop("__actor__", None)
        client.actor_id = None
        release_worker(client)

    def actor_worker_pid(self, actor_id: ActorID) -> Optional[int]:
        with self._lock:
            client = self._actor_workers.get(actor_id)
        return client.proc.pid if client else None

    # -- lifecycle -------------------------------------------------------
    def shutdown(self) -> None:
        # fast lane first: the core is process-global (one rtdc per
        # process), so the next runtime in this process needs it freed
        with self._fast_lock:
            fl, self._fast = self._fast, None
            core, self._fast_core = self._fast_core, None
            lane_workers, self._fast_workers = self._fast_workers, []
        if fl is not None:
            fl.close()
        for w in lane_workers:
            try:
                w.kill(expected=True)
            except Exception:
                pass
        if core is not None:
            try:
                core.stop()
            except Exception:
                pass
        with self._lock:
            actors = dict(self._actor_workers)
            self._actor_workers.clear()
        for actor_id, client in actors.items():
            # Recycle cleanly-shut-down actor workers into the pool (the
            # pool outlives runtimes by design; respawns are expensive).
            with self._lock:
                self._actor_workers[actor_id] = client
            self.discard_actor(actor_id, expected=True)
