"""Declared registry of hello-advertised capability flags.

The driver/daemon wire protocol is version-negotiated per connection:
the daemon's ``handle_hello_driver`` reply advertises what it can do,
the driver stores the bits on its :class:`DaemonHandle` and consults
them before using any capability-gated frame shape. PR-10/11 reviews
caught the same drift by hand four times — a flag advertised but never
checked, or a gated frame sent without checking the peer — so the
shape of the negotiation now lives HERE, as data, and raylint's
``capability-drift`` pass machine-checks all three legs:

- every ``kind: "hello"`` flag is advertised (a key in some
  ``handle_hello*`` reply dict) and its ``guard`` attribute is read
  somewhere (a dead flag is protocol cruft);
- every ``kind: "frame"`` flag is written at some wire send site and
  read (``msg.get(...)``/``msg[...]``) at some receive site;
- every send site of a ``frame`` flag with a non-empty ``requires``
  list is dominated by a check of one of those hello guards — in the
  sending function itself, in a direct caller, or in a helper the
  caller consults (``execute_task`` -> ``_submit_coalescer`` reads
  ``_batch_supported`` before ``_submit_batched`` fires).

Adding a capability: add the hello-reply key + its DaemonHandle guard
attribute here FIRST, then wire the advertiser and the gates — raylint
fails until all legs exist. This dict is parsed statically (it must
stay a pure literal) and imported nowhere hot.
"""

CAPABILITY_FLAGS = {
    # daemon -> driver hello-reply capability bits; "guard" names the
    # DaemonHandle attribute the driver must consult before using the
    # capability on the wire.
    "batch": {
        "kind": "hello",
        "guard": "_batch_supported",
        "doc": "daemon accepts push_task_batch coalesced submissions",
    },
    "result_batch": {
        "kind": "hello",
        "guard": "_result_batch",
        "doc": "daemon batches completions via the reply pump",
    },
    "objectplane": {
        "kind": "hello",
        "guard": "objectplane",
        "doc": "daemon exposes the shm object arena (zero-copy gets)",
    },
    "tenancy": {
        "kind": "hello",
        "guard": "_tenancy_supported",
        "doc": "daemon accepts tenancy_sync job-table frames "
               "(per-job quota/weight federation); drivers that never "
               "see the bit fall back to unconditional admission",
    },
    # driver -> daemon per-frame flags on capability-gated frames;
    # "requires" lists the hello guards that must dominate the send.
    "via_pump": {
        "kind": "frame",
        "requires": ["_result_batch"],
        "doc": "submit_task completion may ride the reply pump",
    },
    "term_pump": {
        "kind": "frame",
        "requires": ["_result_batch", "_batch_supported"],
        "doc": "terminations for this task may ride the reply pump",
    },
    "slot_ok": {
        "kind": "frame",
        "requires": [],
        "doc": "this driver understands ext-slot object grants "
               "(self-describing: reflects the sender's own ability)",
    },
    "async_core": {
        "kind": "hello",
        "guard": "_async_core_remote",
        "doc": "daemon runs the single-threaded asyncio wire+dispatch "
               "core (cfg().async_core). Frames are byte-identical "
               "across cores, so this bit gates NOTHING on the wire — "
               "it exists so mixed clusters are observable (driver "
               "stats name which peers run which core) and so a future "
               "release can retire the threaded fallback knowingly",
    },
    "fence": {
        "kind": "hello",
        "guard": "_fence_supported",
        "doc": "daemon stamps its registration epoch (ep) and the task "
               "attempt (att) into result/termination frames so the "
               "driver can fence stale deliveries across healed "
               "partitions",
    },
    "ep": {
        "kind": "frame",
        "requires": [],
        "doc": "daemon registration epoch stamped on a result frame "
               "(self-describing: an unstamped frame is simply never "
               "fenced, so no hello guard dominates the send)",
    },
    "att": {
        "kind": "frame",
        "requires": [],
        "doc": "task attempt number stamped on a result frame "
               "(self-describing, like ep)",
    },
}
