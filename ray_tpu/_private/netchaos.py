"""Deterministic network chaos: seeded per-link degradation policies.

Reference capability: the C++ runtime's chaos/netem release suites
(`ray-project/ray` release tests run `tc netem`-style loss/latency/
partition schedules against the GCS and raylet RPC channels). On real
TPU fleets the hardest control-plane failures are *transport-level
partial failures* — links that are slow, lossy, one-way, or flapping
while every process stays alive — so this module makes every
control-plane byte stream degradable **deterministically**, below the
frame layer, without touching kernel qdiscs.

A :class:`LinkPolicy` describes one directed (src-role, dst-role,
link-id) edge:

========= ===============================================================
knob      effect per frame while the policy window is active
========= ===============================================================
``lat``   fixed latency, milliseconds
``jitter``extra uniform(0..jitter) ms drawn from the policy's seeded RNG
``bw``    bandwidth cap in bytes/sec (sleep ``nbytes / bw``)
``drop``  drop probability (the frame vanishes; framing stays intact
          because the WHOLE frame is suppressed, never a byte prefix)
``dup``   duplicate-delivery probability (the frame is sent twice)
``partition`` drop everything (a hard one-way partition)
``sym``   also install the mirrored ``dst>src`` policy
``start`` window start, ms after the link's first consult
``dur``   window length ms (0 = open-ended)
``flap``  ``on/off`` ms pair: within the window the impairment cycles
========= ===============================================================

Send-side hooks see frames leaving this process toward ``dst``;
recv-side hooks see frames arriving from ``src``. Because both ends of
a cluster inherit the driver's environment, one env spec degrades a
link consistently from whichever process touches it — and a policy for
the *reverse* direction activated in only one process yields a true
one-way partition (requests leave, replies never arrive, or vice
versa).

Windows are measured from the policy's **first consult** on the link
(not from process start), so an env-armed daemon can boot, register,
and heartbeat before its partition opens — deterministic
partition-then-heal schedules inside subprocesses with no driver RPC
needed.

Activation mirrors ``failpoints.py`` exactly:

- env var ``RAY_TPU_NET_CHAOS`` (parsed at import; spawned daemons /
  head / workers inherit it) with ``RAY_TPU_NET_CHAOS_SEED``;
- config flags ``net_chaos`` / ``net_chaos_seed`` at ``ray_tpu.init``;
- programmatically: :func:`activate` / :func:`configure` /
  :func:`reset`.

Spec grammar (``;``-separated)::

    src>dst[@link]=mod[:mod...]
    mod := lat=<ms> | jitter=<ms> | bw=<bytes_per_s> | drop=<p>
         | dup=<p> | partition | sym | start=<ms> | dur=<ms>
         | flap=<on_ms>/<off_ms>

e.g. ``RAY_TPU_NET_CHAOS='driver>daemon=drop=0.3;``
``daemon>head=partition:start=500:dur=2000'``. ``*`` wildcards any
role / link id.

Fast path: when nothing is configured the wire helpers pay ONE
module-global boolean check (``if netchaos.ENABLED:``) — the disarmed
send/recv path is the pre-existing code path, no policy object is ever
consulted (tier-1 asserts this).

Failpoint seams (observable by chaos schedules / assertions):
``net.link_drop`` fires for every chaos-dropped frame;
``net.partition_heal`` fires when a policy's impairment window closes
(partition healed / flap flipped off).
"""

from __future__ import annotations

import os
import random
import threading
import time
import weakref
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private import failpoints as _fp

__all__ = [
    "ENABLED", "DROP_FRAME", "DUP_FRAME", "LinkPolicy",
    "activate", "configure", "reset", "set_local_role", "local_role",
    "register_link", "on_send", "on_recv",
    "hit_log", "injected_count", "describe",
]

# Module-global guard rebound by activate()/reset(). Wire helpers read
# it as `netchaos.ENABLED` — a single module-dict lookup — before
# paying anything else.
ENABLED = False


class _Verdict:
    __slots__ = ("_name",)

    def __init__(self, name: str):
        self._name = name

    def __repr__(self) -> str:  # pragma: no cover - repr only
        return f"<netchaos.{self._name}>"


DROP_FRAME = _Verdict("DROP_FRAME")
DUP_FRAME = _Verdict("DUP_FRAME")

# this process's role on the cluster graph ("driver" | "head" |
# "daemon" | "worker"); set once at boot by the respective main
_LOCAL_ROLE = ""

# socket -> (peer_role, link_id, local_role_override). socket.socket
# defines __slots__, so identity is kept OUTSIDE the object; weak keys
# mean a closed+collected socket cannot pin its link entry.
_LINKS: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def set_local_role(role: str) -> None:
    global _LOCAL_ROLE
    _LOCAL_ROLE = role


def local_role() -> str:
    return _LOCAL_ROLE


def register_link(sock, peer_role: str, link_id: str = "",
                  local_role: Optional[str] = None) -> None:
    """Tag a socket with the identity of the peer it reaches. Cold
    path (once per connection); safe to call whether or not chaos is
    armed so late programmatic activation still finds every link."""
    try:
        _LINKS[sock] = (peer_role, link_id, local_role)
    except TypeError:       # pragma: no cover - non-weakrefable stub
        pass


class LinkPolicy:
    """One directed link's degradation schedule. Deterministic: the
    per-policy RNG is seeded from (registry seed, src>dst@link), so
    the same seed and the same frame sequence replay the same drop /
    dup / jitter schedule regardless of other policies."""

    __slots__ = ("src", "dst", "link", "lat_ms", "jitter_ms", "bw_bps",
                 "drop_p", "dup_p", "partition", "start_ms", "dur_ms",
                 "flap_on_ms", "flap_off_ms", "rng", "first_use",
                 "consults", "drops", "dups", "delays", "_impairing")

    def __init__(self, src: str = "*", dst: str = "*", link: str = "*",
                 lat_ms: float = 0.0, jitter_ms: float = 0.0,
                 bw_bps: float = 0.0, drop_p: float = 0.0,
                 dup_p: float = 0.0, partition: bool = False,
                 start_ms: float = 0.0, dur_ms: float = 0.0,
                 flap_on_ms: float = 0.0, flap_off_ms: float = 0.0):
        self.src = src or "*"
        self.dst = dst or "*"
        self.link = link or "*"
        self.lat_ms = float(lat_ms)
        self.jitter_ms = float(jitter_ms)
        self.bw_bps = float(bw_bps)
        self.drop_p = float(drop_p)
        self.dup_p = float(dup_p)
        self.partition = bool(partition)
        self.start_ms = float(start_ms)
        self.dur_ms = float(dur_ms)
        self.flap_on_ms = float(flap_on_ms)
        self.flap_off_ms = float(flap_off_ms)
        self.rng = random.Random()      # re-seeded on install
        self.first_use: Optional[float] = None
        self.consults = 0
        self.drops = 0
        self.dups = 0
        self.delays = 0
        self._impairing = False

    @property
    def key(self) -> str:
        return f"{self.src}>{self.dst}@{self.link}"

    def matches(self, src: str, dst: str, link: str) -> bool:
        return ((self.src == "*" or self.src == src)
                and (self.dst == "*" or self.dst == dst)
                and (self.link == "*" or self.link == link))

    def _window_open(self, now: float) -> bool:
        if self.first_use is None:
            self.first_use = now
        elapsed_ms = (now - self.first_use) * 1000.0
        if elapsed_ms < self.start_ms:
            return False
        if self.dur_ms and elapsed_ms >= self.start_ms + self.dur_ms:
            return False
        if self.flap_on_ms:
            period = self.flap_on_ms + self.flap_off_ms
            phase = (elapsed_ms - self.start_ms) % period
            return phase < self.flap_on_ms
        return True

    def decide(self, nbytes: int,
               now: Optional[float] = None) -> Tuple[Optional[str],
                                                     float, bool]:
        """One frame's fate: (effect, delay_s, healed). ``effect`` in
        {"drop", "dup", None}; ``healed`` is True exactly once per
        impaired->clear window transition (partition heal / flap-off).
        Pure decision — the caller sleeps / drops / duplicates."""
        self.consults += 1
        open_ = self._window_open(time.monotonic()
                                  if now is None else now)
        healed = False
        if not open_:
            if self._impairing:
                self._impairing = False
                healed = True
            return None, 0.0, healed
        self._impairing = True
        if self.partition or (self.drop_p
                              and self.rng.random() < self.drop_p):
            self.drops += 1
            return "drop", 0.0, False
        delay_s = self.lat_ms / 1000.0
        if self.jitter_ms:
            delay_s += self.rng.random() * self.jitter_ms / 1000.0
        if self.bw_bps:
            delay_s += nbytes / self.bw_bps
        if delay_s:
            self.delays += 1
        if self.dup_p and self.rng.random() < self.dup_p:
            self.dups += 1
            return "dup", delay_s, False
        return None, delay_s, False


class Registry:
    """Seeded per-link policy registry with a thread-safe hit log."""

    def __init__(self, seed: Optional[int] = None):
        self._policies: List[LinkPolicy] = []
        self._log: List[Dict[str, Any]] = []
        self._lock = threading.Lock()
        self.seed = seed

    def install(self, pol: LinkPolicy) -> None:
        # per-policy RNG derived from (seed, key): one link's draws
        # cannot perturb another's — the same seed replays the same
        # per-link schedule even when traffic interleaves differently
        if self.seed is not None:
            pol.rng = random.Random(f"{self.seed}:{pol.key}")
        with self._lock:
            self._policies.append(pol)

    def active(self) -> bool:
        with self._lock:
            return bool(self._policies)

    def apply(self, src: str, dst: str, link: str, nbytes: int,
              defer: bool = False):
        """Consult policies for one frame. With ``defer=False`` (the
        threaded wire) latency/bandwidth delays are slept here and the
        verdict alone is returned. With ``defer=True`` (the asyncio
        wire, which must never sleep on the loop) the return is a
        ``(verdict, delay_s)`` pair and the CALLER owes the delay —
        typically a per-connection ``call_later`` chain so delayed
        frames still serialize per link but not across links."""
        pol = None
        with self._lock:
            for p in self._policies:    # first match wins
                if p.matches(src, dst, link):
                    pol = p
                    break
            if pol is None:
                return (None, 0.0) if defer else None
            effect, delay_s, healed = pol.decide(nbytes)
            if effect is not None or delay_s:
                _COUNTS[effect or "delay"] = \
                    _COUNTS.get(effect or "delay", 0) + 1
                self._log.append({
                    "src": src, "dst": dst, "link": link,
                    "policy": pol.key, "effect": effect or "delay",
                    "nbytes": nbytes, "ts": time.time()})
        # seam fires and sleeps run OUTSIDE the lock: a delayed frame
        # must not serialize every other link behind it
        if healed and _fp.ENABLED:
            _fp.fire("net.partition_heal", src=src, dst=dst, link=link)
        if delay_s > 0 and not defer:
            time.sleep(delay_s)
        if effect == "drop":
            if _fp.ENABLED:
                _fp.fire("net.link_drop", src=src, dst=dst, link=link)
            return (DROP_FRAME, delay_s) if defer else DROP_FRAME
        if effect == "dup":
            return (DUP_FRAME, delay_s) if defer else DUP_FRAME
        return (None, delay_s) if defer else None

    def log(self, key: Optional[str] = None) -> List[Dict[str, Any]]:
        with self._lock:
            if key is None:
                return list(self._log)
            return [e for e in self._log if e["policy"] == key]

    def describe(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {p.key: {"lat": p.lat_ms, "jitter": p.jitter_ms,
                            "bw": p.bw_bps, "drop": p.drop_p,
                            "dup": p.dup_p, "partition": p.partition,
                            "start": p.start_ms, "dur": p.dur_ms,
                            "flap": (p.flap_on_ms, p.flap_off_ms),
                            "consults": p.consults, "drops": p.drops,
                            "dups": p.dups, "delays": p.delays}
                    for p in self._policies}


# injected-effect counters: plain dict adds (same lossy-tolerant
# discipline as rpc._WIRE); surfaced as
# ray_tpu_link_chaos_injected_total{effect} via chaos_metric_entries()
_COUNTS: Dict[str, int] = {}

_registry = Registry()


def _split_name(name: str) -> Tuple[str, str, str]:
    """``src>dst[@link]`` -> (src, dst, link)."""
    if ">" not in name:
        raise ValueError(f"malformed link {name!r} "
                         f"(expected src>dst[@link])")
    src, _, rest = name.partition(">")
    dst, _, link = rest.partition("@")
    return src.strip(), dst.strip(), link.strip() or "*"


def parse_spec(spec: str) -> List[LinkPolicy]:
    policies: List[LinkPolicy] = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        name, sep, rhs = part.partition("=")
        if not sep:
            raise ValueError(f"malformed link policy {part!r} "
                             f"(expected src>dst[@link]=mods)")
        src, dst, link = _split_name(name)
        kw: Dict[str, Any] = {}
        sym = False
        for mod in rhs.split(":"):
            mod = mod.strip()
            if not mod:
                continue
            k, _, v = mod.partition("=")
            k = k.strip()
            if k == "lat":
                kw["lat_ms"] = float(v)
            elif k == "jitter":
                kw["jitter_ms"] = float(v)
            elif k == "bw":
                kw["bw_bps"] = float(v)
            elif k == "drop":
                kw["drop_p"] = float(v)
            elif k == "dup":
                kw["dup_p"] = float(v)
            elif k == "partition":
                kw["partition"] = True
            elif k == "sym":
                sym = True
            elif k == "start":
                kw["start_ms"] = float(v)
            elif k == "dur":
                kw["dur_ms"] = float(v)
            elif k == "flap":
                on_ms, _, off_ms = v.partition("/")
                kw["flap_on_ms"] = float(on_ms)
                kw["flap_off_ms"] = float(off_ms or on_ms)
            else:
                raise ValueError(f"unknown net-chaos modifier {k!r}")
        policies.append(LinkPolicy(src, dst, link, **kw))
        if sym:
            policies.append(LinkPolicy(dst, src, link, **kw))
    return policies


def activate(spec: str = "", seed: Optional[int] = None) -> Registry:
    """Install a fresh registry from ``spec`` and enable the hooks. An
    empty spec still enables the registry (policies can be added with
    :func:`configure`)."""
    global _registry, ENABLED
    reg = Registry(seed)
    for pol in parse_spec(spec):
        reg.install(pol)
    _registry = reg
    ENABLED = True
    return reg


def configure(pol: LinkPolicy) -> LinkPolicy:
    """Add one policy programmatically (enables the registry)."""
    global ENABLED
    _registry.install(pol)
    ENABLED = True
    return pol


def reset() -> None:
    """Disarm: the wire helpers go back to the one-boolean no-op path.
    Also clears the env form so later-spawned processes start clean."""
    global _registry, ENABLED
    ENABLED = False
    _registry = Registry()
    _COUNTS.clear()
    os.environ.pop("RAY_TPU_NET_CHAOS", None)
    os.environ.pop("RAY_TPU_NET_CHAOS_SEED", None)


def _edge(sock, outbound: bool) -> Tuple[str, str, str]:
    link = _LINKS.get(sock)
    if link is None:
        peer, lid, local = "", "", None
    else:
        peer, lid, local = link
    me = local if local is not None else _LOCAL_ROLE
    if outbound:
        return me, peer, lid or "*"
    return peer, me, lid or "*"


def on_send(sock, nbytes: int) -> Optional[_Verdict]:
    """Frame leaving this process. Returns None, DROP_FRAME, or
    DUP_FRAME — after applying latency / bandwidth sleeps. Call sites
    guard with ``if netchaos.ENABLED:`` so the disarmed path stays
    the pre-existing code path."""
    src, dst, lid = _edge(sock, outbound=True)
    return _registry.apply(src, dst, lid, nbytes)


def on_recv(sock, nbytes: int) -> Optional[_Verdict]:
    """Frame arriving at this process (matched against the REVERSE
    direction: peer -> local). DUP is a send-side effect; recv returns
    None or DROP_FRAME."""
    src, dst, lid = _edge(sock, outbound=False)
    v = _registry.apply(src, dst, lid, nbytes)
    return DROP_FRAME if v is DROP_FRAME else None


def on_send_decide(sock, nbytes: int) -> Tuple[Optional[_Verdict], float]:
    """``on_send`` for the asyncio wire: returns (verdict, delay_s)
    WITHOUT sleeping — the event loop owes the delay via call_later."""
    src, dst, lid = _edge(sock, outbound=True)
    return _registry.apply(src, dst, lid, nbytes, defer=True)


def on_recv_decide(sock, nbytes: int) -> Tuple[Optional[_Verdict], float]:
    """``on_recv`` for the asyncio wire: no sleep, dup suppressed (dup
    is a send-side effect, matching the threaded path)."""
    src, dst, lid = _edge(sock, outbound=False)
    v, delay_s = _registry.apply(src, dst, lid, nbytes, defer=True)
    return (DROP_FRAME if v is DROP_FRAME else None), delay_s


# -- introspection (test assertions) ----------------------------------
def hit_log(key: Optional[str] = None) -> List[Dict[str, Any]]:
    return _registry.log(key)


def injected_count(effect: Optional[str] = None) -> int:
    if effect is not None:
        return _COUNTS.get(effect, 0)
    return sum(_COUNTS.values())


def describe() -> Dict[str, Dict[str, Any]]:
    return _registry.describe()


def chaos_metric_entries() -> list:
    """Injected-effect counters in the export_snapshot wire-entry
    format (merged into the exposition via rpc.wire_metric_entries)."""
    if not _COUNTS:
        return []
    return [{
        "name": "ray_tpu_link_chaos_injected_total", "kind": "counter",
        "description": "network-chaos effects injected on control-plane "
                       "links, by effect",
        "samples": [[[["effect", e]], v]
                    for e, v in sorted(_COUNTS.items())],
    }]


def maybe_activate_from_config(cfg) -> None:
    """``ray_tpu.init`` hook: the ``net_chaos`` flag activates the
    registry for this process AND exports the env form so processes
    spawned later (daemons, head, workers) replay the same spec."""
    spec = getattr(cfg, "net_chaos", "")
    if not spec or ENABLED:
        return
    seed = int(getattr(cfg, "net_chaos_seed", 0) or 0)
    os.environ["RAY_TPU_NET_CHAOS"] = spec
    if seed:
        os.environ["RAY_TPU_NET_CHAOS_SEED"] = str(seed)
    activate(spec, seed=seed or None)


# env activation: daemons/head/workers are spawned with the driver's
# environment, so one export degrades the whole cluster's links
# deterministically
_env_spec = os.environ.get("RAY_TPU_NET_CHAOS", "")
if _env_spec:
    activate(_env_spec,
             seed=int(os.environ.get("RAY_TPU_NET_CHAOS_SEED", "0")
                      or 0) or None)
del _env_spec
