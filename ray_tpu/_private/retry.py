"""Unified retry/backoff policy for control-plane reconnect loops.

Reference capability: the single retryable-gRPC policy of the reference
(``src/ray/rpc/retryable_grpc_client.h`` — every GCS/raylet client
shares one backoff/timeout discipline) instead of per-call-site sleep
constants. Every loop that re-dials a peer (head redial, daemon
head-reconnect, fast-lane reconnect, task retry) goes through a
:class:`RetryPolicy`, so backoff behavior is uniform, bounded, and
observable:

- exponential backoff with FULL JITTER (sleep ~ U(0, min(cap,
  base*mult^attempt)) — the AWS-style decorrelated herd breaker);
- an attempt budget (``max_attempts``) and/or an overall deadline
  (``deadline_s``); per-attempt work can bound itself with
  ``attempt_timeout_s`` (carried on the policy for the call site);
- counters exported through the existing Prometheus registry
  (``ray_tpu_retries_total`` / ``ray_tpu_retry_backoff_seconds_total``
  / ``ray_tpu_retry_exhausted_total``, labeled by loop name).

Usage::

    policy = RetryPolicy.default(deadline_s=grace)
    client = policy.run(lambda: HeadClient(addr),
                        loop="daemon.head_reconnect",
                        retry_on=(OSError, RpcError))

On exhaustion the LAST exception re-raises, so call sites keep their
existing error contracts (``RpcError`` from a head call, ``OSError``
from a connect).
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional, Tuple, Type

_rng = random.Random()
_rng_lock = threading.Lock()


def _counter(name: str, desc: str):
    # get-or-create by name on every use: the metrics registry may be
    # cleared between sessions and a cached instance would go dark
    from ray_tpu.util.metrics import Counter
    return Counter(name, desc, tag_keys=("loop",))


def record_retry(loop: str, backoff_s: float = 0.0) -> None:
    """Count one retry (and its backoff) for ``loop`` in the Prometheus
    registry. Used by :meth:`RetryPolicy.run` and by retry paths that
    manage their own resubmission (the task-retry path)."""
    tags = {"loop": loop}
    _counter("ray_tpu_retries_total",
             "control-plane retry attempts by loop").inc(tags=tags)
    if backoff_s > 0:
        _counter("ray_tpu_retry_backoff_seconds_total",
                 "total seconds slept in retry backoff by loop").inc(
                     backoff_s, tags=tags)


def record_exhausted(loop: str) -> None:
    _counter("ray_tpu_retry_exhausted_total",
             "retry loops that gave up (budget/deadline hit)").inc(
                 tags={"loop": loop})


@dataclass(frozen=True)
class RetryPolicy:
    """Immutable backoff schedule; share instances freely across threads."""

    max_attempts: int = 0          # total fn invocations; 0 = unbounded
    base_s: float = 0.05           # first backoff cap
    max_backoff_s: float = 2.0     # backoff cap ceiling
    multiplier: float = 2.0
    deadline_s: float = 0.0        # overall budget; 0 = none
    attempt_timeout_s: float = 0.0 # advisory per-attempt bound (0 = none)
    jitter: bool = True            # full jitter; False = deterministic cap

    @classmethod
    def default(cls, **overrides) -> "RetryPolicy":
        """Policy seeded from the central flag table (config.py)."""
        from ray_tpu._private.config import cfg
        base = {"base_s": cfg().retry_base_backoff_s,
                "max_backoff_s": cfg().retry_max_backoff_s}
        base.update(overrides)
        return cls(**base)

    def backoff_s(self, attempt: int,
                  rng: Optional[random.Random] = None) -> float:
        """Sleep for the given 0-based failed-attempt index."""
        # exponent clamp: an unlimited-retry task's attempt number can
        # grow past float range; by 64 doublings the cap governs anyway
        cap = min(self.max_backoff_s,
                  self.base_s * (self.multiplier ** min(attempt, 64)))
        if not self.jitter:
            return cap
        if rng is not None:
            return rng.uniform(0.0, cap)
        with _rng_lock:
            return _rng.uniform(0.0, cap)

    def run(self, fn: Callable[[], "object"], *, loop: str,
            retry_on: Tuple[Type[BaseException], ...] = (Exception,),
            rng: Optional[random.Random] = None,
            sleep: Callable[[float], None] = time.sleep,
            abort: Optional[Callable[[], bool]] = None,
            on_retry: Optional[Callable[[BaseException, int], None]]
            = None):
        """Invoke ``fn`` until it returns, an exception outside
        ``retry_on`` escapes, or the budget/deadline runs out (the last
        exception then re-raises). ``abort()`` is polled before each
        backoff so shutdown paths exit promptly; ``on_retry(exc, n)``
        runs before each re-invocation (redial hooks)."""
        deadline = (time.monotonic() + self.deadline_s
                    if self.deadline_s > 0 else None)
        attempt = 0
        while True:
            try:
                return fn()
            except retry_on as e:  # noqa: PERF203 — this IS the loop
                attempt += 1
                now = time.monotonic()
                out_of_budget = (
                    (self.max_attempts and attempt >= self.max_attempts)
                    or (deadline is not None and now >= deadline)
                    or (abort is not None and abort()))
                if out_of_budget:
                    record_exhausted(loop)
                    raise
                delay = self.backoff_s(attempt - 1, rng)
                if deadline is not None:
                    delay = min(delay, max(0.0, deadline - now))
                record_retry(loop, delay)
                if delay > 0:
                    sleep(delay)
                if abort is not None and abort():
                    record_exhausted(loop)
                    raise
                if on_retry is not None:
                    on_retry(e, attempt)


# The task-retry path resubmits through the scheduler rather than
# re-invoking a closure, so it consumes the schedule directly:
# backoff_s(attempt) + record_retry. Short caps — a crash-looping task
# must not wedge a dispatch thread for seconds.
TASK_RETRY = RetryPolicy(base_s=0.01, max_backoff_s=0.25)


# ---------------------------------------------------------------------------
# shared deferral wheel: ONE daemon thread services every delayed
# callback (per-retry threading.Timer threads explode under a
# node-death fan-out over a large backlog)
# ---------------------------------------------------------------------------

class _TimerWheel:
    def __init__(self):
        import heapq
        self._heapq = heapq
        self._heap: list = []
        self._seq = 0
        self._cv = threading.Condition()
        self._thread: Optional[threading.Thread] = None

    def defer(self, delay_s: float, fn: Callable[[], None]) -> None:
        due = time.monotonic() + max(0.0, delay_s)
        with self._cv:
            self._seq += 1
            self._heapq.heappush(self._heap, (due, self._seq, fn))
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._loop, daemon=True, name="retry-timer")
                self._thread.start()
            self._cv.notify()

    def _loop(self) -> None:
        while True:
            with self._cv:
                while not self._heap:
                    self._cv.wait()
                due, _, fn = self._heap[0]
                now = time.monotonic()
                if due > now:
                    self._cv.wait(due - now)
                    continue
                self._heapq.heappop(self._heap)
            try:
                fn()
            except Exception:   # a resubmit must not kill the wheel
                pass


_wheel = _TimerWheel()


def defer(delay_s: float, fn: Callable[[], None]) -> None:
    """Run ``fn`` after ``delay_s`` on the shared timer thread."""
    _wheel.defer(delay_s, fn)
