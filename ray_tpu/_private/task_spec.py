"""Task specifications and option validation.

Parity contract: the reference's ``common/task/task_spec.h`` (what a task *is*)
and ``python/ray/_private/ray_option_utils.py`` (the validated option surface
of ``@remote``). Options kept 1:1 where they make sense on TPU; ``num_gpus``
is accepted as an alias that maps onto the ``TPU`` resource so reference code
ports cleanly.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private.ids import ActorID, JobID, ObjectID, PlacementGroupID, TaskID


class TaskKind(enum.Enum):
    NORMAL = "normal"
    ACTOR_CREATION = "actor_creation"
    ACTOR_TASK = "actor_task"


# ---------------------------------------------------------------------------
# Option validation (reference: python/ray/_private/ray_option_utils.py)
# ---------------------------------------------------------------------------

COMMON_OPTIONS = {
    "num_cpus", "num_gpus", "num_tpus", "memory", "resources",
    "accelerator_type", "label_selector", "name", "runtime_env",
    "scheduling_strategy", "placement_group", "placement_group_bundle_index",
    "enable_task_events", "_metadata", "_in_process",
}
TASK_ONLY_OPTIONS = {
    "max_calls", "max_retries", "retry_exceptions", "num_returns",
    "_generator_backpressure_num_objects",
}
ACTOR_ONLY_OPTIONS = {
    "concurrency_groups", "lifetime", "max_concurrency", "max_restarts",
    "max_task_retries", "max_pending_calls", "namespace", "get_if_exists",
    "object_store_memory",
}

DEFAULT_TASK_OPTIONS = {"num_cpus": 1, "max_retries": 3, "num_returns": 1}
DEFAULT_ACTOR_OPTIONS = {"num_cpus": 0, "max_restarts": 0,
                         "max_task_retries": 0, "max_concurrency": 1,
                         "max_pending_calls": -1, "lifetime": None}


def validate_options(options: Dict[str, Any], for_actor: bool) -> Dict[str, Any]:
    allowed = COMMON_OPTIONS | (ACTOR_ONLY_OPTIONS if for_actor
                                else TASK_ONLY_OPTIONS)
    for k in options:
        if k not in allowed:
            kind = "actor" if for_actor else "task"
            raise ValueError(f"invalid option {k!r} for a {kind}")
    lifetime = options.get("lifetime")
    if lifetime not in (None, "detached", "non_detached"):
        raise ValueError(f"lifetime must be 'detached'|'non_detached', "
                         f"got {lifetime!r}")
    if options.get("get_if_exists") and not options.get("name"):
        raise ValueError("get_if_exists requires a `name` option")
    nr = options.get("num_returns")
    if nr is not None and not (
            (isinstance(nr, int) and nr >= 0) or nr in ("dynamic", "streaming")):
        raise ValueError(f"num_returns must be int>=0|'dynamic'|'streaming', "
                         f"got {nr!r}")
    for res_opt in ("num_cpus", "num_gpus", "num_tpus", "memory"):
        v = options.get(res_opt)
        if v is not None and (not isinstance(v, (int, float)) or v < 0):
            raise ValueError(f"{res_opt} must be a non-negative number")
    return options


def resources_from_options(options: Dict[str, Any]) -> Dict[str, float]:
    """Flatten option fields into a single resource-demand dict."""
    resources: Dict[str, float] = {}
    if options.get("num_cpus"):
        resources["CPU"] = float(options["num_cpus"])
    # num_gpus aliases onto the TPU chip resource in this framework.
    tpus = options.get("num_tpus", options.get("num_gpus"))
    if tpus:
        resources["TPU"] = float(tpus)
    if options.get("memory"):
        resources["memory"] = float(options["memory"])
    for k, v in (options.get("resources") or {}).items():
        if k in ("CPU", "TPU", "memory") and k in resources:
            raise ValueError(f"resource {k} specified twice")
        resources[k] = float(v)
    return resources


# ---------------------------------------------------------------------------
# Scheduling strategies (reference: python/ray/util/scheduling_strategies.py)
# ---------------------------------------------------------------------------

@dataclass
class PlacementGroupSchedulingStrategy:
    placement_group: Any
    placement_group_bundle_index: int = -1
    placement_group_capture_child_tasks: bool = False


@dataclass
class NodeAffinitySchedulingStrategy:
    node_id: str  # hex
    soft: bool = False


@dataclass
class NodeLabelSchedulingStrategy:
    hard: Optional[Dict[str, Any]] = None
    soft: Optional[Dict[str, Any]] = None


# "DEFAULT" | "SPREAD" | one of the strategy classes
SchedulingStrategyT = Any


# ---------------------------------------------------------------------------
# Task spec
# ---------------------------------------------------------------------------

@dataclass
class TaskSpec:
    task_id: TaskID
    kind: TaskKind
    name: str
    # The callable: for NORMAL, the function; for ACTOR_CREATION, the class;
    # for ACTOR_TASK, the method name (callable resolved on the actor).
    func: Any
    args: Tuple = ()
    kwargs: Dict[str, Any] = field(default_factory=dict)
    resources: Dict[str, float] = field(default_factory=dict)
    num_returns: Any = 1
    return_ids: List[ObjectID] = field(default_factory=list)
    max_retries: int = 0
    retry_exceptions: Any = False  # bool | list of exception types
    scheduling_strategy: SchedulingStrategyT = "DEFAULT"
    job_id: Optional[JobID] = None
    # actor fields
    actor_id: Optional[ActorID] = None
    method_name: str = ""
    seqno: int = 0
    concurrency_group: str = ""
    # actor creation fields
    max_restarts: int = 0
    max_task_retries: int = 0
    max_concurrency: int = 1
    max_pending_calls: int = -1
    concurrency_groups: Optional[Dict[str, int]] = None
    lifetime: Optional[str] = None
    actor_name: Optional[str] = None
    namespace: Optional[str] = None
    # per-method option defaults declared via @ray_tpu.method (actor creation)
    method_options: Dict[str, Dict[str, Any]] = field(default_factory=dict)
    # placement group capture
    placement_group_id: Optional[PlacementGroupID] = None
    bundle_index: int = -1
    # original (un-scoped) demand, kept so retries can re-match bundles
    # after resources were rewritten onto bundle-scoped names
    pg_demand: Optional[Dict[str, float]] = None
    pg_capture: bool = False  # propagate the PG to child tasks
    # lineage/retry accounting
    attempt_number: int = 0
    # generator backpressure
    backpressure_num_objects: int = -1
    enable_task_events: bool = True
    # TPU-first placement: force execution in the mesh-owning host
    # process (SPMD mesh actors, accelerator-touching work) instead of a
    # spawned worker process. Internal option set by Train/Serve/LLM.
    in_process: bool = False
    enqueued_at: float = 0.0
    # distributed trace context (stamped by events.stamp_trace at submit;
    # rides the slim spec to daemons/workers so every process records
    # spans for the same trace): see docs/observability.md
    trace_id: str = ""
    trace_sampled: bool = False
    submit_wall: float = 0.0
    submit_mono: float = 0.0
    label_selector: Optional[Dict[str, Any]] = None
    runtime_env: Optional[Dict[str, Any]] = None

    def dependencies(self) -> List[ObjectID]:
        """ObjectIDs this task's args depend on (top-level refs only)."""
        from ray_tpu._private.object_ref import ObjectRef

        deps = []
        for a in list(self.args) + list(self.kwargs.values()):
            if isinstance(a, ObjectRef):
                deps.append(a.id)
        return deps
