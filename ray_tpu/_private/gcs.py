"""Global Control Service: cluster-wide state and pubsub.

Parity contract (reference ``src/ray/gcs/gcs_server/``): node membership
(GcsNodeManager), actor directory + named actors (GcsActorManager), placement
group table (GcsPlacementGroupManager), internal KV (GcsInternalKVManager),
job table, and a pubsub bus for state change notifications. In this build the
GCS is an in-process service owned by the Runtime; the interface is designed
so a later round can put gRPC in front of it for true multi-host operation
without changing callers.
"""

from __future__ import annotations

import enum
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu._private.ids import ActorID, JobID, NodeID, PlacementGroupID


class ActorState(enum.Enum):
    PENDING = "PENDING_CREATION"
    ALIVE = "ALIVE"
    RESTARTING = "RESTARTING"
    DEAD = "DEAD"


@dataclass
class ActorInfo:
    actor_id: ActorID
    name: Optional[str]
    namespace: str
    state: ActorState = ActorState.PENDING
    node_id: Optional[NodeID] = None
    num_restarts: int = 0
    max_restarts: int = 0
    max_task_retries: int = 0
    detached: bool = False
    death_cause: Optional[str] = None
    creation_spec: Any = None  # TaskSpec for restarts
    class_name: str = ""
    method_options: Dict[str, Dict[str, Any]] = field(default_factory=dict)


@dataclass
class NodeInfo:
    node_id: NodeID
    alive: bool = True
    resources: Dict[str, float] = field(default_factory=dict)
    labels: Dict[str, str] = field(default_factory=dict)
    start_time: float = field(default_factory=time.time)


@dataclass
class JobInfo:
    job_id: JobID
    start_time: float = field(default_factory=time.time)
    metadata: Dict[str, str] = field(default_factory=dict)


class Pubsub:
    """In-process pubsub bus (reference: src/ray/pubsub long-poll channels)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._subs: Dict[str, List[Callable[[Any], None]]] = {}

    def subscribe(self, channel: str, cb: Callable[[Any], None]) -> None:
        with self._lock:
            self._subs.setdefault(channel, []).append(cb)

    def publish(self, channel: str, msg: Any) -> None:
        with self._lock:
            cbs = list(self._subs.get(channel, []))
        for cb in cbs:
            try:
                cb(msg)
            except Exception:
                pass


class GCS:
    def __init__(self):
        self._lock = threading.RLock()
        self.nodes: Dict[NodeID, NodeInfo] = {}
        self.actors: Dict[ActorID, ActorInfo] = {}
        self._named_actors: Dict[Tuple[str, str], ActorID] = {}
        self._kv: Dict[str, Dict[bytes, bytes]] = {}
        self.jobs: Dict[JobID, JobInfo] = {}
        self.placement_groups: Dict[PlacementGroupID, Any] = {}
        self.pubsub = Pubsub()

    # -- nodes -------------------------------------------------------------
    def register_node(self, info: NodeInfo) -> None:
        from ray_tpu._private.export_events import emit_export
        emit_export("NODE", node_id=info.node_id.hex(), state="ALIVE",
                    resources=dict(info.resources))
        with self._lock:
            self.nodes[info.node_id] = info
        self.pubsub.publish("node", ("added", info.node_id))

    def mark_node_dead(self, node_id: NodeID) -> None:
        with self._lock:
            info = self.nodes.get(node_id)
            if info is None or not info.alive:
                return   # duplicate/unknown: no event, no publish
            info.alive = False
        from ray_tpu._private.export_events import emit_export
        emit_export("NODE", node_id=node_id.hex(), state="DEAD")
        self.pubsub.publish("node", ("removed", node_id))

    def alive_nodes(self) -> List[NodeInfo]:
        with self._lock:
            return [n for n in self.nodes.values() if n.alive]

    # -- actors ------------------------------------------------------------
    def register_actor_or_get_existing(self, info: ActorInfo):
        """Atomic get_if_exists: returns existing live ActorID or registers.

        Returns (actor_id, created).
        """
        with self._lock:
            existing_id = self._live_named_actor_locked(info.namespace,
                                                        info.name)
            if existing_id is not None:
                return existing_id, False
            self._register_actor_locked(info)
            return info.actor_id, True

    def _live_named_actor_locked(self, namespace: str,
                                 name: Optional[str]):
        if not name:
            return None
        existing_id = self._named_actors.get((namespace, name))
        if existing_id is None:
            return None
        existing = self.actors.get(existing_id)
        if existing is not None and existing.state != ActorState.DEAD:
            return existing_id
        return None

    def register_actor(self, info: ActorInfo) -> None:
        with self._lock:
            self._register_actor_locked(info)

    def _register_actor_locked(self, info: ActorInfo) -> None:
        if info.name:
            if self._live_named_actor_locked(info.namespace,
                                             info.name) is not None:
                raise ValueError(
                    f"actor name {info.name!r} already taken in "
                    f"namespace {info.namespace!r}")
            self._named_actors[(info.namespace, info.name)] = info.actor_id
        self.actors[info.actor_id] = info

    def update_actor_state(self, actor_id: ActorID, state: ActorState,
                           node_id: Optional[NodeID] = None,
                           death_cause: Optional[str] = None) -> None:
        with self._lock:
            info = self.actors.get(actor_id)
            if info is None:
                return
            info.state = state
            if node_id is not None:
                info.node_id = node_id
            if death_cause is not None:
                info.death_cause = death_cause
            if state == ActorState.DEAD and info.name:
                self._named_actors.pop((info.namespace, info.name), None)
        from ray_tpu._private.export_events import emit_export
        emit_export("ACTOR", actor_id=actor_id.hex(), state=str(state),
                    death_cause=death_cause)
        self.pubsub.publish("actor", (actor_id, state))

    def get_actor_info(self, actor_id: ActorID) -> Optional[ActorInfo]:
        with self._lock:
            return self.actors.get(actor_id)

    def get_named_actor(self, name: str, namespace: str) -> Optional[ActorID]:
        with self._lock:
            return self._named_actors.get((namespace, name))

    def list_named_actors(self, all_namespaces: bool = False,
                          namespace: str = "") -> List[Dict[str, str]]:
        with self._lock:
            out = []
            for (ns, name), _aid in self._named_actors.items():
                if all_namespaces or ns == namespace:
                    out.append({"name": name, "namespace": ns})
            return out

    # -- persistence (reference: Redis-backed GCS fault tolerance —
    # gcs_table_storage.h / gcs_init_data.h: on restart the GCS reloads
    # all tables; here the KV + job tables snapshot to a file) --------------
    def snapshot(self, path: str) -> str:
        import pickle
        with self._lock:
            payload = {"kv": {ns: dict(t) for ns, t in self._kv.items()},
                       "jobs": dict(self.jobs)}
        with open(path, "wb") as f:
            pickle.dump(payload, f)
        return path

    def restore(self, path: str) -> None:
        import pickle
        with open(path, "rb") as f:
            payload = pickle.load(f)
        with self._lock:
            self._kv = {ns: dict(t) for ns, t in payload["kv"].items()}
            self.jobs.update(payload.get("jobs", {}))

    # -- internal KV (reference: gcs_kv_manager; used for function table,
    # collective rendezvous, runtime-env URIs) ------------------------------
    def kv_put(self, key: bytes, value: bytes, overwrite: bool = True,
               namespace: bytes = b"") -> bool:
        ns = namespace.decode() if isinstance(namespace, bytes) else namespace
        with self._lock:
            table = self._kv.setdefault(ns, {})
            if not overwrite and key in table:
                return False
            table[key] = value
            return True

    def kv_get(self, key: bytes, namespace: bytes = b"") -> Optional[bytes]:
        ns = namespace.decode() if isinstance(namespace, bytes) else namespace
        with self._lock:
            return self._kv.get(ns, {}).get(key)

    def kv_del(self, key: bytes, namespace: bytes = b"") -> None:
        ns = namespace.decode() if isinstance(namespace, bytes) else namespace
        with self._lock:
            self._kv.get(ns, {}).pop(key, None)

    def kv_keys(self, prefix: bytes = b"", namespace: bytes = b"") -> List[bytes]:
        ns = namespace.decode() if isinstance(namespace, bytes) else namespace
        with self._lock:
            return [k for k in self._kv.get(ns, {}) if k.startswith(prefix)]

    def kv_exists(self, key: bytes, namespace: bytes = b"") -> bool:
        return self.kv_get(key, namespace) is not None
