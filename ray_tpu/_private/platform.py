"""Platform pinning for CPU-simulated device meshes.

The environment may pin JAX onto a TPU plugin (e.g. ``JAX_PLATFORMS=axon``
via sitecustomize); tests and multi-chip dryruns must instead run on N
virtual CPU devices (the reference's in-one-machine cluster fixture idea,
``python/ray/tests/conftest.py:535-588``, applied to SPMD). Both the env
var and the jax config update are required: the env var alone can be
re-pinned by sitecustomize, the config alone loses to an exported
``JAX_PLATFORMS``. Safe to call after ``import jax`` as long as no backend
has been initialized yet.
"""

from __future__ import annotations

import os
import re

_COUNT_FLAG = "--xla_force_host_platform_device_count"


def pin_cpu_env(n_devices: int | None = None) -> None:
    """Env-only half of the pin (no jax import): safe in fresh processes
    where jax has not been imported yet."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    if n_devices:
        flags = os.environ.get("XLA_FLAGS", "")
        repl = f"{_COUNT_FLAG}={n_devices}"
        if _COUNT_FLAG in flags:
            flags = re.sub(rf"{_COUNT_FLAG}=\d+", repl, flags)
        else:
            flags = (flags + " " + repl).strip()
        os.environ["XLA_FLAGS"] = flags


def force_cpu_platform(n_devices: int | None = None) -> None:
    """Pin jax to the host CPU platform, optionally with ``n_devices``
    virtual devices. Must run before the first backend touch
    (``jax.devices()`` etc.); raises if the backend is already up on a
    different platform."""
    pin_cpu_env(n_devices)

    import jax

    jax.config.update("jax_platforms", "cpu")
    if n_devices:
        try:
            jax.config.update("jax_num_cpu_devices", n_devices)
        except Exception:
            pass  # older jax: XLA_FLAGS above already sets the count
