"""Worker log capture + tail-to-driver.

Reference capability: every worker's stdout/stderr goes to per-process
files under the session dir and a log monitor tails new lines back to the
driver, prefixed with the producing worker's identity
(``python/ray/_private/log_monitor.py``, ``worker.py:2164
print_worker_logs``). Here:

- each worker process redirects fds 1/2 to
  ``<log_dir>/worker-<pid>.{out,err}`` at boot (worker_process.py);
- a ``LogMonitor`` thread in the host process (driver, or node daemon in
  cluster mode) tails the directory and hands new lines to a sink;
- the driver prints them as ``(worker pid=N) line``; daemons forward
  lines over the wire (``worker_log`` push) so cross-process workers
  surface on the driver too.

Disable with ``RAY_TPU_LOG_TO_DRIVER=0`` (then worker output inherits the
parent terminal as before).
"""

from __future__ import annotations

import os
import re
import tempfile
import threading
from typing import Callable, Dict, Optional, Tuple

_FILE_RE = re.compile(r"worker-(\d+)\.(out|err)$")

_session_dir: Optional[str] = None
_session_lock = threading.Lock()


def log_to_driver_enabled() -> bool:
    from ray_tpu._private.config import cfg
    return cfg().log_to_driver


def session_log_dir(create: bool = True) -> Optional[str]:
    """This process's worker-log directory (one per driver/daemon)."""
    global _session_dir
    with _session_lock:
        if _session_dir is None and create:
            from ray_tpu._private.config import cfg
            _session_dir = cfg().log_dir or \
                tempfile.mkdtemp(prefix="ray_tpu_logs_")
            os.makedirs(_session_dir, exist_ok=True)
        return _session_dir


def set_session_log_dir(path: str) -> None:
    global _session_dir
    os.makedirs(path, exist_ok=True)
    with _session_lock:
        _session_dir = path


def reset_session_log_dir() -> None:
    global _session_dir
    with _session_lock:
        _session_dir = None


def redirect_process_output(log_dir: str) -> None:
    """Point THIS process's fds 1/2 at per-pid log files (worker boot).
    fd-level dup2 so C/extension writes land there too; line-buffered so
    the monitor sees prints promptly."""
    import sys

    pid = os.getpid()
    for stream, name in ((sys.stdout, "out"), (sys.stderr, "err")):
        path = os.path.join(log_dir, f"worker-{pid}.{name}")
        fd = os.open(path, os.O_CREAT | os.O_WRONLY | os.O_APPEND, 0o644)
        try:
            stream.flush()
        except Exception:
            pass
        os.dup2(fd, stream.fileno())
        os.close(fd)
    try:
        sys.stdout.reconfigure(line_buffering=True)
        sys.stderr.reconfigure(line_buffering=True)
    except Exception:
        pass


class LogMonitor:
    """Tails ``worker-*.{out,err}`` files in a directory, delivering each
    new complete line to ``sink(pid, stream, line)``."""

    def __init__(self, log_dir: str,
                 sink: Callable[[int, str, str], None],
                 interval: float = 0.2, start_at_end: bool = False):
        self.log_dir = log_dir
        self.sink = sink
        self.interval = interval
        self._offsets: Dict[str, int] = {}
        self._partial: Dict[str, str] = {}
        if start_at_end:
            # Skip lines from a previous runtime in this process (the
            # worker pool and its log files outlive init/shutdown).
            try:
                for name in os.listdir(log_dir):
                    if _FILE_RE.search(name):
                        try:
                            self._offsets[name] = os.path.getsize(
                                os.path.join(log_dir, name))
                        except OSError:
                            pass
            except OSError:
                pass
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="log-monitor")
        self._thread.start()

    def stop(self) -> None:
        """Stop and join: the loop's final drain runs on the monitor
        thread, so callers never race it with their own poll_once()."""
        self._stop.set()
        if self._thread is not threading.current_thread():
            self._thread.join(timeout=5.0)

    def poll_once(self) -> None:
        """One scan (also used directly by tests for determinism)."""
        try:
            names = os.listdir(self.log_dir)
        except OSError:
            return
        for name in names:
            m = _FILE_RE.search(name)
            if not m:
                continue
            pid, stream = int(m.group(1)), m.group(2)
            path = os.path.join(self.log_dir, name)
            off = self._offsets.get(name, 0)
            try:
                with open(path, "r", errors="replace") as f:
                    f.seek(off)
                    chunk = f.read()
                    self._offsets[name] = f.tell()
            except OSError:
                continue
            if not chunk:
                continue
            chunk = self._partial.pop(name, "") + chunk
            lines = chunk.split("\n")
            if lines and lines[-1]:
                self._partial[name] = lines[-1]   # incomplete tail
            for line in lines[:-1]:
                if line:
                    try:
                        self.sink(pid, stream, line)
                    except Exception:
                        pass

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            self.poll_once()
        self.poll_once()  # final drain


def make_driver_printer(node_tag: str = ""
                        ) -> Callable[[int, str, str], None]:
    """The driver-side sink: reference ``print_worker_logs`` format."""
    import sys

    prefix = f"{node_tag}, " if node_tag else ""

    def sink(pid: int, stream: str, line: str) -> None:
        out = sys.stderr if stream == "err" else sys.stdout
        print(f"(worker {prefix}pid={pid}) {line}", file=out)

    return sink
