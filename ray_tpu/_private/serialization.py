"""Value serialization with zero-copy buffer handling.

Capability parity with the reference's ``python/ray/_private/serialization.py``:
cloudpickle for arbitrary Python values, pickle protocol-5 out-of-band buffers
for zero-copy numpy/Arrow payloads, and in-band ObjectRef capture so references
nested inside values keep their identity (and pin their lineage) across the
store boundary.

TPU-first difference: ``jax.Array`` values are serialized as host numpy views
when they must cross a host boundary, but within a host the object store keeps
the live device array (HBM tier) and never copies through host memory — see
:mod:`ray_tpu._private.object_store`.
"""

from __future__ import annotations

import io
import pickle
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import cloudpickle

from ray_tpu._private import device_objects


@dataclass
class SerializedValue:
    """A pickled payload plus its out-of-band buffers and captured refs."""

    inband: bytes
    buffers: List[pickle.PickleBuffer] = field(default_factory=list)
    # ObjectRefs discovered inside the value during serialization. The owner
    # must keep these alive while the serialized copy exists (borrowed refs).
    nested_refs: List[Any] = field(default_factory=list)

    @property
    def total_bytes(self) -> int:
        n = len(self.inband)
        for b in self.buffers:
            n += b.raw().nbytes
        return n


class SerializationContext:
    """Per-process serializer with custom reducer registry."""

    def __init__(self):
        self._custom_serializers: Dict[type, tuple] = {}
        self._lock = threading.Lock()

    def register_custom_serializer(self, cls: type,
                                   serializer: Callable[[Any], Any],
                                   deserializer: Callable[[Any], Any]) -> None:
        with self._lock:
            self._custom_serializers[cls] = (serializer, deserializer)

    def deregister_custom_serializer(self, cls: type) -> None:
        with self._lock:
            self._custom_serializers.pop(cls, None)

    def serialize(self, value: Any) -> SerializedValue:
        from ray_tpu._private.object_ref import ObjectRef

        buffers: List[pickle.PickleBuffer] = []
        nested_refs: List[ObjectRef] = []

        buf = io.BytesIO()
        pickler = cloudpickle.CloudPickler(
            buf, protocol=5, buffer_callback=buffers.append
        )

        custom = self._custom_serializers
        # delegate to cloudpickle's own reducer_override — it is how
        # local functions/classes get pickled; shadowing it breaks them
        base = pickler.reducer_override

        def reducer_override(obj):
            if isinstance(obj, ObjectRef):
                nested_refs.append(obj)
                return (ObjectRef._rehydrate, (obj.id, obj.owner_hex()))
            ser = custom.get(type(obj))
            if ser is not None:
                serializer, deserializer = ser
                return (_apply_deserializer, (deserializer, serializer(obj)))
            # device (HBM) objects: jax's own pickle reducer collapses
            # NamedShardings to a single device — ours round-trips the
            # sharding meta so the consumer rematerializes on an
            # equivalent mesh (_private/device_objects.py)
            if device_objects.is_jax_array(obj):
                return device_objects.jax_reduce(obj)
            return base(obj)

        pickler.reducer_override = reducer_override
        pickler.dump(value)
        return SerializedValue(buf.getvalue(), buffers, nested_refs)

    def deserialize(self, sv: SerializedValue) -> Any:
        return pickle.loads(sv.inband, buffers=sv.buffers)


def _apply_deserializer(deserializer, payload):
    return deserializer(payload)


def check_serializable(value: Any) -> Optional[str]:
    """Return None if value serializes cleanly, else the error string.

    Parity with the reference's ``ray.util.check_serialize`` inspector.
    """
    try:
        SerializationContext().serialize(value)
        return None
    except Exception as e:  # noqa: BLE001 - report any failure to the user
        return f"{type(e).__name__}: {e}"
