"""Cross-process device (HBM) objects: sharding-preserving wire format.

Reference capability: ``python/ray/experimental/gpu_object_manager/
gpu_object_manager.py:18`` — GPU tensors crossing process/host
boundaries without losing their device placement (the reference moves
them with NCCL; collective transport).

TPU-native design: within one host process the object store keeps the
LIVE ``jax.Array`` in its HBM tier and consumers get it zero-copy
(``_private/object_store.py`` device tier). Only when a value crosses a
PROCESS boundary (daemon worker -> driver, node -> node) does it pass
through here: the array serializes as **(host bytes, dtype string,
sharding meta)** and the consumer rematerializes it with
``jax.device_put`` — re-sharded onto an equivalent local mesh when the
consumer has enough devices, single-device otherwise. jax's built-in
pickle reducer drops NamedShardings to SingleDeviceSharding; this one
round-trips them.
"""

from __future__ import annotations

import io
from typing import Any, Optional, Tuple


def jax_reduce(obj) -> Tuple:
    """The (callable, args) reduce tuple for a jax.Array — the single
    definition shared by every ray_tpu pickler."""
    return (rebuild_jax_array, (reduce_jax_array(obj),))


def wire_dumps(value: Any) -> bytes:
    """cloudpickle.dumps with the sharding-preserving jax.Array reducer,
    SCOPED to this pickler only. Never touches copyreg's process-global
    dispatch table — user code's pickle/copy.deepcopy semantics for
    jax.Arrays stay exactly jax's own. Every ray_tpu wire boundary that
    may carry user values must dump through here."""
    import cloudpickle

    buf = io.BytesIO()
    pickler = cloudpickle.CloudPickler(buf, protocol=5)
    # delegate to cloudpickle's own reducer_override — it is how local
    # functions/classes get pickled; shadowing it outright breaks them
    base = pickler.reducer_override

    def reducer_override(obj):
        if is_jax_array(obj):
            return jax_reduce(obj)
        return base(obj)

    pickler.reducer_override = reducer_override
    pickler.dump(value)
    return buf.getvalue()


def is_jax_array(obj: Any) -> bool:
    """Cheap check that avoids importing jax for non-jax values."""
    if not type(obj).__module__.startswith(("jax", "jaxlib")):
        return False
    try:
        import jax
    except ImportError:
        return False
    return isinstance(obj, jax.Array)


def _spec_to_wire(spec) -> Tuple:
    """PartitionSpec entries are str | tuple[str, ...] | None — already
    picklable; normalize to a plain tuple."""
    return tuple(spec)


def reduce_jax_array(arr) -> Tuple:
    """(host_numpy, sharding_meta). The numpy payload carries the dtype
    itself (ml_dtypes covers bf16). Raises for non-fully-addressable
    arrays (a multi-host global array cannot be pulled to one process;
    ship per-host shards instead)."""
    import jax
    import numpy as np

    if not arr.is_fully_addressable:
        raise ValueError(
            "cannot serialize a non-fully-addressable jax.Array across "
            "a process boundary; fetch per-host shards or use "
            "multihost collectives")
    meta: Optional[Tuple] = None
    sh = arr.sharding
    if isinstance(sh, jax.sharding.NamedSharding):
        mesh = sh.mesh
        meta = ("named", tuple(mesh.axis_names),
                tuple(mesh.devices.shape), _spec_to_wire(sh.spec))
    host = np.asarray(arr)        # device -> host copy (one transfer)
    return host, meta


def rebuild_jax_array(payload: Tuple):
    """Rematerialize on the consumer: same named sharding when the
    local device count allows, else default placement."""
    host, meta = payload
    import jax
    import numpy as np

    if meta is not None and meta[0] == "named":
        _, axis_names, mesh_shape, spec = meta
        need = int(np.prod(mesh_shape))
        devs = jax.devices()
        if len(devs) >= need:
            mesh = jax.sharding.Mesh(
                np.asarray(devs[:need]).reshape(mesh_shape), axis_names)
            sharding = jax.sharding.NamedSharding(
                mesh, jax.sharding.PartitionSpec(*spec))
            return jax.device_put(host, sharding)
    return jax.device_put(host)
