"""Structured export events (reference: `src/ray/util/event.cc` +
`protobuf/export_*.proto` + `_private/event/export_event_logger.py` —
task/actor/node/job/train state changes written as JSONL for external
pipelines; shipped by the aggregator agent in the reference)."""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Optional

EXPORT_TYPES = ("TASK", "ACTOR", "NODE", "JOB", "TRAIN_RUN",
                "PLACEMENT_GROUP")


class ExportEventLogger:
    """One JSONL file per event type under ``<session>/export_events/``."""

    def __init__(self, session_dir: str):
        self.dir = os.path.join(session_dir, "export_events")
        os.makedirs(self.dir, exist_ok=True)
        self._locks: Dict[str, threading.Lock] = {
            t: threading.Lock() for t in EXPORT_TYPES}

    def emit(self, event_type: str, payload: Dict[str, Any]) -> None:
        if event_type not in self._locks:
            raise ValueError(f"unknown export event type {event_type!r}")
        record = {"event_type": event_type, "timestamp": time.time(),
                  **payload}
        path = os.path.join(self.dir, f"event_{event_type}.jsonl")
        with self._locks[event_type]:
            with open(path, "a") as f:
                f.write(json.dumps(record, default=str) + "\n")

    def read(self, event_type: str):
        path = os.path.join(self.dir, f"event_{event_type}.jsonl")
        if not os.path.exists(path):
            return []
        with open(path) as f:
            return [json.loads(line) for line in f if line.strip()]


_logger: Optional[ExportEventLogger] = None


def get_export_logger() -> Optional[ExportEventLogger]:
    """Lazily bind to the running session (None before init)."""
    global _logger
    if _logger is None:
        from ray_tpu._private import worker as _worker
        rt = _worker.global_runtime()
        if rt is None:
            return None
        _logger = ExportEventLogger(rt.session_dir)
    return _logger


def reset_export_logger() -> None:
    global _logger
    _logger = None
    _pending.clear()


def export_enabled() -> bool:
    from ray_tpu._private.config import cfg
    return cfg().export_events


# Events emitted during Runtime.__init__ (e.g. the first NODE ALIVE)
# happen before the global runtime binds; buffer them until it does.
_pending: list = []
_PENDING_CAP = 1000


def emit_export(event_type: str, **payload: Any) -> None:
    """Emit one structured event if exporting is enabled (the
    ``RAY_CONFIG enable_export_api_*`` role). Never raises: export is
    observability, not control flow."""
    try:
        if not export_enabled():
            return
        logger = get_export_logger()
        if logger is None:
            if len(_pending) < _PENDING_CAP:
                _pending.append((event_type, dict(payload),
                                 time.time()))
            return
        while _pending:
            etype, pl, ts = _pending.pop(0)
            logger.emit(etype, {**pl, "timestamp": ts})
        logger.emit(event_type, payload)
    except Exception:
        pass
