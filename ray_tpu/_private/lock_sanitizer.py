"""Lock-order sanitizer: deadlock-cycle detection for runtime locks.

Reference capability: the reference runs TSAN builds in CI
(`.buildkite/`, SURVEY §5.2) to catch lock-order inversions in the C++
core. The Python runtime's equivalent discipline: an opt-in sanitizer
(``RAY_TPU_LOCK_SANITIZER=1`` or ``_system_config={"lock_sanitizer":
True}``) that wraps named runtime locks, records the per-thread
held-lock set at every acquisition, builds the global acquired-before
graph, and reports the FIRST cycle (a potential deadlock) with both
acquisition stacks. Zero overhead when disabled — ``tracked_lock``
returns a plain lock.

Used by the core runtime's central locks (object store, refcount,
scheduler); tests drive it directly and through the stress suite.
"""

from __future__ import annotations

import os
import threading
import traceback
from typing import Dict, List, Optional, Set, Tuple


def enabled() -> bool:
    if os.environ.get("RAY_TPU_LOCK_SANITIZER") == "1":
        return True
    try:
        from ray_tpu._private.config import cfg
        return bool(cfg().lock_sanitizer)
    except Exception:
        return False


class LockOrderViolation(RuntimeWarning):
    pass


class _Graph:
    """acquired-before edges between lock CLASSES (names) + first-seen
    stacks. Class-level like Linux lockdep: an inversion between any
    two instances of two classes is a discipline violation even if
    those exact instances never deadlock. Same-class nested acquisition
    of DISTINCT instances is skipped (would need lockdep-style nesting
    annotations to express)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._edges: Dict[str, Set[str]] = {}
        self._stacks: Dict[Tuple[str, str], str] = {}
        self._reported: Set[Tuple[str, str]] = set()
        self.violations: List[str] = []

    def add(self, held: List[Tuple[str, int]], acquiring: str,
            acquiring_id: int) -> Optional[str]:
        with self._lock:
            for h_name, h_id in held:
                if h_id == acquiring_id:
                    continue            # true re-entrancy: same instance
                if h_name == acquiring:
                    continue            # same class, distinct instance
                edge = (h_name, acquiring)
                if acquiring not in self._edges.setdefault(h_name, set()):
                    self._edges[h_name].add(acquiring)
                    self._stacks[edge] = "".join(
                        traceback.format_stack(limit=8)[:-2])
                # cycle check: does a path acquiring -> ... -> h exist?
                if self._reaches(acquiring, h_name):
                    if edge in self._reported:
                        continue        # dedupe: one report per edge
                    self._reported.add(edge)
                    report = self._report(h_name, acquiring)
                    self.violations.append(report)
                    return report
        return None

    def _reaches(self, src: str, dst: str) -> bool:
        seen = set()
        stack = [src]
        while stack:
            cur = stack.pop()
            if cur == dst:
                return True
            if cur in seen:
                continue
            seen.add(cur)
            stack.extend(self._edges.get(cur, ()))
        return False

    def _report(self, held: str, acquiring: str) -> str:
        fwd = self._stacks.get((held, acquiring), "<first sighting>")
        rev = self._stacks.get((acquiring, held), "<reverse edge on a path>")
        return (f"lock-order inversion: {held!r} -> {acquiring!r} "
                f"conflicts with an existing {acquiring!r} ->...-> "
                f"{held!r} path\n--- this acquisition ---\n{fwd}"
                f"--- conflicting order first seen ---\n{rev}")

    def reset(self) -> None:
        with self._lock:
            self._edges.clear()
            self._stacks.clear()
            self._reported.clear()
            self.violations.clear()


GRAPH = _Graph()
_tls = threading.local()


def _held() -> List[str]:
    held = getattr(_tls, "held", None)
    if held is None:
        held = _tls.held = []
    return held


class TrackedLock:
    """Lock wrapper feeding the acquired-before graph. Violations are
    recorded (and warned) rather than raised — the sanitizer must never
    turn a latent hazard into a live crash."""

    def __init__(self, name: str, reentrant: bool = True):
        self.name = name
        self._lock = (threading.RLock() if reentrant
                      else threading.Lock())

    def acquire(self, blocking: bool = True, timeout: float = -1):
        report = GRAPH.add(_held(), self.name, id(self))
        if report is not None:
            import warnings
            warnings.warn(report, LockOrderViolation, stacklevel=2)
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            _held().append((self.name, id(self)))
        return ok

    def release(self) -> None:
        held = _held()
        key = (self.name, id(self))
        for i in range(len(held) - 1, -1, -1):   # last occurrence
            if held[i] == key:
                del held[i]
                break
        self._lock.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


def tracked_lock(name: str, reentrant: bool = True):
    """A named runtime lock: sanitized when enabled, plain otherwise.
    ``reentrant=False`` preserves plain-Lock semantics on both paths."""
    if enabled():
        return TrackedLock(name, reentrant=reentrant)
    return threading.RLock() if reentrant else threading.Lock()
