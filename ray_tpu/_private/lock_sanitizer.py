"""Lock-order sanitizer + contention meter for runtime locks.

Reference capability: the reference runs TSAN builds in CI
(`.buildkite/`, SURVEY §5.2) to catch lock-order inversions in the C++
core. The Python runtime's equivalent discipline: an opt-in sanitizer
(``RAY_TPU_LOCK_SANITIZER=1`` or ``_system_config={"lock_sanitizer":
True}``) that wraps named runtime locks, records the per-thread
held-lock set at every acquisition, builds the global acquired-before
graph, and reports the FIRST cycle (a potential deadlock) with both
acquisition stacks. Zero overhead when disabled — ``tracked_lock``
returns a plain lock.

A second opt-in mode (``RAY_TPU_LOCK_METRICS=1`` /
``_system_config={"lock_metrics": True}``) swaps in
:class:`MeteredLock`: wait-time and hold-time histograms plus a
contended counter per lock NAME, exported as
``ray_tpu_lock_wait_seconds{lock}`` / ``ray_tpu_lock_hold_seconds{lock}``
/ ``ray_tpu_lock_contended_total{lock}`` through
``metrics.export_snapshot`` (so daemon lock stats federate to the head
like every other metric). The sanitizer wins when both are set — the
two wrappers answer different questions and stacking them would tax
the very paths being measured.

Used by the core runtime's central locks (object store, refcount,
scheduler); tests drive it directly and through the stress suite.
"""

from __future__ import annotations

import os
import threading
import time
import traceback
from typing import Dict, List, Optional, Set, Tuple


def enabled() -> bool:
    if os.environ.get("RAY_TPU_LOCK_SANITIZER") == "1":
        return True
    try:
        from ray_tpu._private.config import cfg
        return bool(cfg().lock_sanitizer)
    except Exception:
        return False


class LockOrderViolation(RuntimeWarning):
    pass


class _Graph:
    """acquired-before edges between lock CLASSES (names) + first-seen
    stacks. Class-level like Linux lockdep: an inversion between any
    two instances of two classes is a discipline violation even if
    those exact instances never deadlock. Same-class nested acquisition
    of DISTINCT instances is skipped (would need lockdep-style nesting
    annotations to express)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._edges: Dict[str, Set[str]] = {}
        self._stacks: Dict[Tuple[str, str], str] = {}
        self._reported: Set[Tuple[str, str]] = set()
        self.violations: List[str] = []

    def add(self, held: List[Tuple[str, int]], acquiring: str,
            acquiring_id: int) -> Optional[str]:
        with self._lock:
            for h_name, h_id in held:
                if h_id == acquiring_id:
                    continue            # true re-entrancy: same instance
                if h_name == acquiring:
                    continue            # same class, distinct instance
                edge = (h_name, acquiring)
                if acquiring not in self._edges.setdefault(h_name, set()):
                    self._edges[h_name].add(acquiring)
                    self._stacks[edge] = "".join(
                        traceback.format_stack(limit=8)[:-2])
                # cycle check: does a path acquiring -> ... -> h exist?
                if self._reaches(acquiring, h_name):
                    if edge in self._reported:
                        continue        # dedupe: one report per edge
                    self._reported.add(edge)
                    report = self._report(h_name, acquiring)
                    self.violations.append(report)
                    return report
        return None

    def _reaches(self, src: str, dst: str) -> bool:
        seen = set()
        stack = [src]
        while stack:
            cur = stack.pop()
            if cur == dst:
                return True
            if cur in seen:
                continue
            seen.add(cur)
            stack.extend(self._edges.get(cur, ()))
        return False

    def _report(self, held: str, acquiring: str) -> str:
        fwd = self._stacks.get((held, acquiring), "<first sighting>")
        rev = self._stacks.get((acquiring, held), "<reverse edge on a path>")
        return (f"lock-order inversion: {held!r} -> {acquiring!r} "
                f"conflicts with an existing {acquiring!r} ->...-> "
                f"{held!r} path\n--- this acquisition ---\n{fwd}"
                f"--- conflicting order first seen ---\n{rev}")

    def reset(self) -> None:
        with self._lock:
            self._edges.clear()
            self._stacks.clear()
            self._reported.clear()
            self.violations.clear()


GRAPH = _Graph()
_tls = threading.local()


def _held() -> List[str]:
    held = getattr(_tls, "held", None)
    if held is None:
        held = _tls.held = []
    return held


class TrackedLock:
    """Lock wrapper feeding the acquired-before graph. Violations are
    recorded (and warned) rather than raised — the sanitizer must never
    turn a latent hazard into a live crash."""

    def __init__(self, name: str, reentrant: bool = True):
        self.name = name
        self._lock = (threading.RLock() if reentrant
                      else threading.Lock())

    def acquire(self, blocking: bool = True, timeout: float = -1):
        report = GRAPH.add(_held(), self.name, id(self))
        if report is not None:
            import warnings
            warnings.warn(report, LockOrderViolation, stacklevel=2)
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            _held().append((self.name, id(self)))
        return ok

    def release(self) -> None:
        held = _held()
        key = (self.name, id(self))
        for i in range(len(held) - 1, -1, -1):   # last occurrence
            if held[i] == key:
                del held[i]
                break
        self._lock.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


# ---------------------------------------------------------------------------
# contention meter (the observability twin of the sanitizer)
# ---------------------------------------------------------------------------

def metering_enabled() -> bool:
    if os.environ.get("RAY_TPU_LOCK_METRICS") == "1":
        return True
    try:
        from ray_tpu._private.config import cfg
        return bool(cfg().lock_metrics)
    except Exception:
        return False


# Shared wait/hold bucket boundaries (seconds). 100µs..1s covers the
# control plane's spectrum: uncontended acquires land in the first
# bucket, a convoying ledger lock shows up in the 1-100ms ones.
METER_BOUNDS = (0.0001, 0.001, 0.01, 0.1, 1.0)

_METER_REG_LOCK = threading.Lock()
#: guarded by _METER_REG_LOCK (name -> live MeteredLock instances)
_METERED: Dict[str, List["MeteredLock"]] = {}


class MeteredLock:
    """Lock wrapper measuring wait (time blocked acquiring) and hold
    (time held, outermost acquire→release for RLocks) into per-instance
    histogram buckets, aggregated per NAME by
    :func:`lock_metric_entries`.

    Contention is detected with a non-blocking probe, so the
    uncontended fast path pays one extra C call and no clock read for
    wait. Bucket counters are mutated only while the measured lock is
    HELD — self-serialized, no second lock. Reads (the exporter) are
    lockless and may observe a torn in-progress update; a snapshot
    being off by one observation is acceptable for monitoring."""

    __slots__ = ("name", "_lock", "_reentrant", "_tls", "_hold_t0",
                 "wait_counts", "wait_sum", "wait_total",
                 "hold_counts", "hold_sum", "hold_total", "contended")

    def __init__(self, name: str, reentrant: bool = True):
        self.name = name
        self._lock = (threading.RLock() if reentrant
                      else threading.Lock())
        self._reentrant = reentrant
        self._tls = threading.local()
        self._hold_t0 = 0.0             # non-reentrant holder's t0
        n = len(METER_BOUNDS) + 1
        #: guarded by self._lock (mutated only while holding it)
        self.wait_counts = [0] * n
        self.wait_sum = 0.0
        self.wait_total = 0
        self.hold_counts = [0] * n
        self.hold_sum = 0.0
        self.hold_total = 0
        self.contended = 0
        with _METER_REG_LOCK:
            _METERED.setdefault(name, []).append(self)

    @staticmethod
    def _bucket(counts: List[int], value: float) -> None:
        i = 0
        while i < len(METER_BOUNDS) and value > METER_BOUNDS[i]:
            i += 1
        counts[i] += 1

    def acquire(self, blocking: bool = True, timeout: float = -1):
        if self._reentrant and getattr(self._tls, "depth", 0):
            ok = self._lock.acquire(blocking, timeout)
            if ok:
                self._tls.depth += 1
            return ok
        if self._lock.acquire(False):       # uncontended fast path
            wait = 0.0
            was_contended = False
        else:
            if not blocking:
                return False
            t0 = time.perf_counter()
            if not self._lock.acquire(True, timeout):
                return False
            wait = time.perf_counter() - t0
            was_contended = True
        now = time.perf_counter()
        if self._reentrant:
            self._tls.depth = 1
            self._tls.hold_t0 = now
        else:
            self._hold_t0 = now
        # the lock IS held here — taken by the explicit acquire calls
        # above, which the with-block checker cannot see
        self._bucket(self.wait_counts, wait)  # raylint: disable=guarded-by
        self.wait_sum += wait
        self.wait_total += 1
        if was_contended:
            self.contended += 1
        return True

    def release(self) -> None:
        if self._reentrant:
            depth = getattr(self._tls, "depth", 1)
            if depth > 1:
                self._tls.depth = depth - 1
                self._lock.release()
                return
            t0 = getattr(self._tls, "hold_t0", 0.0)
            self._tls.depth = 0
        else:
            t0 = self._hold_t0
        hold = time.perf_counter() - t0 if t0 else 0.0
        self._bucket(self.hold_counts, hold)
        self.hold_sum += hold
        self.hold_total += 1
        self._lock.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


def lock_metric_entries() -> List[Dict]:
    """Per-name aggregates of every live MeteredLock, in the
    ``metrics.export_snapshot`` wire-entry format (hooked in there, so
    daemon lock metrics federate to the head automatically). Empty when
    metering never engaged."""
    with _METER_REG_LOCK:
        by_name = {name: list(insts) for name, insts in _METERED.items()}
    wait_rows, hold_rows, contended = [], [], []
    n = len(METER_BOUNDS) + 1
    for name in sorted(by_name):
        wc, ws, wt = [0] * n, 0.0, 0
        hc, hs, ht = [0] * n, 0.0, 0
        cont = 0
        for inst in by_name[name]:
            for i, c in enumerate(inst.wait_counts):
                wc[i] += c
            ws += inst.wait_sum
            wt += inst.wait_total
            for i, c in enumerate(inst.hold_counts):
                hc[i] += c
            hs += inst.hold_sum
            ht += inst.hold_total
            cont += inst.contended
        if not wt and not ht:
            continue                    # constructed but never acquired
        label = [["lock", name]]
        wait_rows.append([label, wc, ws, wt])
        hold_rows.append([label, hc, hs, ht])
        contended.append([label, cont])
    out: List[Dict] = []
    if wait_rows:
        out.append({"name": "ray_tpu_lock_wait_seconds",
                    "kind": "histogram",
                    "description": "time blocked acquiring a tracked "
                                   "runtime lock (lock_metrics mode)",
                    "boundaries": list(METER_BOUNDS),
                    "hist": wait_rows})
        out.append({"name": "ray_tpu_lock_hold_seconds",
                    "kind": "histogram",
                    "description": "time a tracked runtime lock was "
                                   "held (outermost acquire->release)",
                    "boundaries": list(METER_BOUNDS),
                    "hist": hold_rows})
        out.append({"name": "ray_tpu_lock_contended_total",
                    "kind": "counter",
                    "description": "acquisitions that blocked on a "
                                   "tracked runtime lock",
                    "samples": contended})
    return out


def tracked_lock(name: str, reentrant: bool = True):
    """A named runtime lock: sanitized when the sanitizer is enabled,
    metered when lock_metrics is, plain otherwise. ``reentrant=False``
    preserves plain-Lock semantics on every path."""
    if enabled():
        return TrackedLock(name, reentrant=reentrant)
    if metering_enabled():
        return MeteredLock(name, reentrant=reentrant)
    return threading.RLock() if reentrant else threading.Lock()
