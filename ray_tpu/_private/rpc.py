"""Typed RPC layer: length-prefixed msgpack frames over TCP.

This is the control-plane wire of the distributed runtime — the role gRPC
plays in the reference (``src/ray/rpc``, 37 protos; e.g.
``protobuf/node_manager.proto:394-494``, ``gcs_service.proto:68-860``).
Design choices, TPU-first rationale:

- The accelerator data plane NEVER rides this wire: tensors move via XLA
  collectives over ICI inside jitted programs, or via the shm object
  store between same-host processes. RPC carries control messages and
  (pickled) host-plane payloads only.
- Typed messages: every method has a declared field schema
  (``SCHEMAS``); send() validates required fields so protocol drift is
  caught at the caller, like proto field checks.
- Framing: ``u32 length | msgpack map``. msgpack handles bytes natively,
  so serialized task payloads embed without base64.

Server model: one decode thread per connection feeding a shared handler
pool — requests PIPELINE (the reference multiplexes gRPC streams the
same way). Per-connection arrival order is preserved for ordinary
handlers via a FIFO lane; handlers that may block mark themselves
``@concurrent`` to run outside the lane. Dispatch is by method name to
a service object (``handle_<method>``). A handler may return ``HOLD``
to park the request (long-poll; reference ``pubsub/publisher.h:300``)
and complete it later via ``Connection.reply``.
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Optional, Tuple

import msgpack

from ray_tpu._private import failpoints as _fp
from ray_tpu._private import netchaos as _nc

_LEN = struct.Struct("!I")
MAX_FRAME = 1 << 31


class RpcError(Exception):
    """Transport-level failure (peer died, protocol violation)."""


class RemoteError(Exception):
    """The remote handler raised; message carries the remote repr."""


class _Hold:
    """Sentinel: handler parked the request for a deferred reply."""


HOLD = _Hold()


def concurrent(handler):
    """Mark a handler as safe to run OUTSIDE its connection's FIFO lane.

    Use for handlers that may block (e.g. a 120s object pull): they run
    directly on the dispatch pool so they cannot head-of-line-block other
    requests from the same peer. Everything unmarked keeps strict
    per-connection arrival order (actor-call ordering relies on it)."""
    handler._rpc_concurrent = True
    return handler


def loop_safe(handler):
    """Mark a handler as non-blocking: on the async core it runs INLINE
    on the event loop (parse -> handler -> reply with zero thread
    hand-offs; the reply joins the peer's coalesced write batch). The
    contract is strict — no lock that a non-loop thread holds across
    blocking work, no socket/file I/O, no pool waits; anything heavier
    must be staged to an executor by the handler itself. Ordering note:
    loop_safe frames keep arrival order among THEMSELVES (loop FIFO)
    but may run ahead of earlier lane-queued methods from the same
    peer. The threaded core ignores the marker (lane semantics)."""
    handler._rpc_loop_safe = True
    return handler


# ---------------------------------------------------------------------------
# message schemas (the "proto file"): method -> required field names
# ---------------------------------------------------------------------------

SCHEMAS: Dict[str, Tuple[str, ...]] = {}


def declare(method: str, *fields: str) -> None:
    SCHEMAS[method] = fields


def _validate(method: str, kw: Dict[str, Any]) -> None:
    fields = SCHEMAS.get(method)
    if fields is None:
        raise RpcError(f"undeclared rpc method {method!r}")
    missing = [f for f in fields if f not in kw]
    if missing:
        raise RpcError(f"{method}: missing fields {missing}")


# ---------------------------------------------------------------------------
# wire instrumentation (reference: grpc server/client interceptors feeding
# the metrics agent). Hot-path updates are PLAIN dict/int ops — a rare lost
# increment under a race is acceptable for byte/frame counters; the
# per-method request counters and the inflight gauge take the small lock.
# Surfaced through the registry exposition via wire_metric_entries()
# (metrics.export_snapshot), so daemon wire stats federate to the head.
# ---------------------------------------------------------------------------

_WIRE_LOCK = threading.Lock()
_WIRE = {"bytes_sent": 0, "bytes_recv": 0,
         "frames_sent": 0, "frames_recv": 0, "inflight": 0}
_WIRE_CLIENT_REQS: Dict[str, int] = {}
_WIRE_SERVER_REQS: Dict[str, int] = {}


def wire_metric_entries() -> list:
    """This process's wire counters as metric-snapshot entries (the
    export_snapshot wire format: label keys as [[k, v], ...])."""
    with _WIRE_LOCK:
        client = dict(_WIRE_CLIENT_REQS)
        server = dict(_WIRE_SERVER_REQS)
        inflight = _WIRE["inflight"]
    out = [
        {"name": "ray_tpu_rpc_inflight", "kind": "gauge",
         "description": "RPC requests awaiting a reply in this process",
         "samples": [[[], inflight]]},
        {"name": "ray_tpu_wire_bytes_total", "kind": "counter",
         "description": "bytes moved on the control-plane wire",
         "samples": [[[["direction", "sent"]], _WIRE["bytes_sent"]],
                     [[["direction", "recv"]], _WIRE["bytes_recv"]]]},
        {"name": "ray_tpu_wire_frames_total", "kind": "counter",
         "description": "frames moved on the control-plane wire",
         "samples": [[[["direction", "sent"]], _WIRE["frames_sent"]],
                     [[["direction", "recv"]], _WIRE["frames_recv"]]]},
    ]
    if client:
        out.append({
            "name": "ray_tpu_rpc_client_requests_total", "kind": "counter",
            "description": "outbound RPC requests by method",
            "samples": [[[["method", m]], v]
                        for m, v in sorted(client.items())]})
    if server:
        out.append({
            "name": "ray_tpu_rpc_server_requests_total", "kind": "counter",
            "description": "inbound RPC requests by method",
            "samples": [[[["method", m]], v]
                        for m, v in sorted(server.items())]})
    out.extend(_nc.chaos_metric_entries())
    return out


# Above this size the `len + blob` concatenation copy costs more than a
# second syscall: send header and payload as two sendalls under the lock
# (zero extra copy); below it, one small concat + one syscall wins.
SEND_CONCAT_MAX = 64 * 1024


def send_frame_bytes(sock: socket.socket, blob, wlock) -> None:
    """Length-prefixed frame write, shared by rpc and the fast lane.
    ``blob`` is any bytes-like; large payloads are never copied into a
    `len + blob` concatenation. ``wlock`` is the connection's
    write-serialization lock — holding it across the sendall is the
    contract (frames must not interleave), which is why it must never
    double as a ledger lock."""
    n = len(blob)
    if n > MAX_FRAME:
        raise RpcError(f"frame too large: {n}")
    if _nc.ENABLED:
        # chaos sits BELOW the frame layer: a drop suppresses the WHOLE
        # frame (never a byte prefix, so framing stays intact); a dup
        # delivers the same complete frame twice back-to-back
        verdict = _nc.on_send(sock, n + 4)
        if verdict is _nc.DROP_FRAME:
            return
        if verdict is _nc.DUP_FRAME:
            _WIRE["bytes_sent"] += n + 4
            _WIRE["frames_sent"] += 1
            with wlock:
                if n <= SEND_CONCAT_MAX:
                    sock.sendall(_LEN.pack(n) + blob)
                else:
                    sock.sendall(_LEN.pack(n))
                    sock.sendall(blob)
    _WIRE["bytes_sent"] += n + 4    # lossy-tolerant plain add (hot path)
    _WIRE["frames_sent"] += 1
    if n <= SEND_CONCAT_MAX:
        with wlock:
            sock.sendall(_LEN.pack(n) + blob)
        return
    with wlock:
        # two-phase write under the SAME lock hold: the header and its
        # payload must stay adjacent on the stream
        sock.sendall(_LEN.pack(n))
        sock.sendall(blob)


def _send_frame(sock: socket.socket, obj: Dict[str, Any],
                wlock: threading.Lock) -> None:
    send_frame_bytes(sock, msgpack.packb(obj, use_bin_type=True), wlock)


def recv_exact(sock: socket.socket, n: int) -> bytearray:
    """Read exactly ``n`` bytes via recv_into on one preallocated
    buffer — no per-chunk bytes allocation + copy. ONE implementation
    for both wire layers (rpc + fast_lane). Raises ConnectionError on
    EOF (an OSError subclass, so existing transport-failure handling on
    both sides catches it unchanged)."""
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:])
        if not r:
            raise ConnectionError("connection closed")
        got += r
    return buf


def _recv_frame(sock: socket.socket) -> Dict[str, Any]:
    while True:
        (n,) = _LEN.unpack(recv_exact(sock, 4))
        _WIRE["bytes_recv"] += n + 4    # lossy-tolerant plain add (hot path)
        _WIRE["frames_recv"] += 1
        blob = recv_exact(sock, n)
        if _nc.ENABLED and _nc.on_recv(sock, n + 4) is _nc.DROP_FRAME:
            continue        # inbound frame lost on the simulated link
        return msgpack.unpackb(blob, raw=False)


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------

class Client:
    """One TCP connection to a Server; thread-safe request/reply."""

    def __init__(self, addr: Tuple[str, int], timeout: float = 30.0,
                 on_push: Optional[Callable[[str, Dict[str, Any]], None]]
                 = None):
        self.addr = addr
        self._sock = socket.create_connection(addr, timeout=10.0)
        self._sock.settimeout(None)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._wlock = threading.Lock()
        self._id = 0                        #: guarded by self._id_lock
        self._id_lock = threading.Lock()
        self._pending: Dict[int, list] = {}  #: guarded by self._plock
        self._plock = threading.Lock()
        self._timeout = timeout
        self._on_push = on_push
        self.dead = False
        self._reader = threading.Thread(target=self._read_loop, daemon=True,
                                        name=f"rpc-client-{addr[1]}")
        self._reader.start()

    def link(self, peer_role: str, link_id: str = "") -> "Client":
        """Tag this connection's socket with the peer's chaos-link
        identity (cold path; chainable: ``Client(addr).link("head")``)."""
        _nc.register_link(self._sock, peer_role, link_id)
        return self

    def _read_loop(self) -> None:
        try:
            while True:
                msg = _recv_frame(self._sock)
                if _fp.ENABLED and _fp.fire(
                        "rpc.client.recv",
                        method=msg.get("m", "")) is _fp.DROP:
                    continue    # reply/push lost in transit
                rid = msg.get("i")
                if rid is None:
                    # server push (no correlation id)
                    if self._on_push is not None:
                        try:
                            self._on_push(msg.get("m", ""), msg)
                        except Exception:
                            pass
                    continue
                with self._plock:
                    slot = self._pending.pop(rid, None)
                if slot is not None:
                    slot[1] = msg
                    slot[0].set()
        except Exception:   # transport death AND injected faults: any
            # reader exit must fail pending slots, or timeout=None
            # callers hang forever on a zombie connection
            self._fail_all()

    def _fail_all(self) -> None:
        self.dead = True
        with self._plock:
            pending, self._pending = self._pending, {}
        for slot in pending.values():
            slot[1] = None
            slot[0].set()

    def call(self, method: str, timeout: Optional[float] = None,
             **kw) -> Dict[str, Any]:
        """Blocking request/reply. Raises RemoteError on handler error,
        RpcError on transport failure."""
        _validate(method, kw)
        if self.dead:
            raise RpcError(f"connection to {self.addr} is dead")
        with _WIRE_LOCK:
            _WIRE_CLIENT_REQS[method] = \
                _WIRE_CLIENT_REQS.get(method, 0) + 1
            _WIRE["inflight"] += 1
        try:
            return self._call_counted(method, timeout, kw)
        finally:
            with _WIRE_LOCK:
                _WIRE["inflight"] -= 1

    def _call_counted(self, method: str, timeout: Optional[float],
                      kw: Dict[str, Any]) -> Dict[str, Any]:
        # failpoint BEFORE the pending slot exists: an error arm must
        # not leak a slot; a DROP arm skips the send so the caller times
        # out exactly like real frame loss
        dropped = (_fp.ENABLED and _fp.fire(
            "rpc.client.send", method=method) is _fp.DROP)
        if dropped and (timeout if timeout is not None
                        else self._timeout) is None:
            # a deadline-less caller (long-poll subscribers) can never
            # observe a lost frame as a timeout — surface the drop as
            # transport failure instead of wedging the waiter forever
            # (on healthy TCP, silent frame loss IS connection death)
            self._fail_all()
            raise RpcError(f"send to {self.addr} dropped by failpoint")
        with self._id_lock:
            self._id += 1
            rid = self._id
        slot = [threading.Event(), None]
        with self._plock:
            self._pending[rid] = slot
        msg = dict(kw)
        msg["m"] = method
        msg["i"] = rid
        try:
            if not dropped:
                _send_frame(self._sock, msg, self._wlock)
        except (OSError, RpcError):
            self._fail_all()
            raise RpcError(f"send to {self.addr} failed")
        if not slot[0].wait(timeout if timeout is not None
                            else self._timeout):
            with self._plock:
                self._pending.pop(rid, None)
            raise RpcError(f"{method} to {self.addr} timed out")
        reply = slot[1]
        if reply is None:
            raise RpcError(f"connection to {self.addr} died during "
                           f"{method}")
        if reply.get("e"):
            raise RemoteError(reply["e"])
        return reply

    def notify(self, method: str, **kw) -> None:
        """Fire-and-forget (no reply expected)."""
        _validate(method, kw)
        if (_fp.ENABLED and _fp.fire("rpc.client.send",
                                     method=method) is _fp.DROP):
            return              # notification lost in transit
        msg = dict(kw)
        msg["m"] = method
        try:
            _send_frame(self._sock, msg, self._wlock)
        except (OSError, RpcError):
            self._fail_all()
            raise RpcError(f"send to {self.addr} failed")

    def close(self) -> None:
        self.dead = True
        try:
            # a bare close() does NOT wake a reader blocked in recv()
            # (the fd may even be reused); shutdown() delivers EOF so
            # the reader exits and deadline-less callers unblock
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        self._fail_all()    # idempotent: close() means dead for callers


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------

class Connection:
    """Server-side handle to one client connection."""

    def __init__(self, sock: socket.socket, peer):
        self.sock = sock
        self.peer = peer
        self.wlock = threading.Lock()
        self.meta: Dict[str, Any] = {}   # services stash identity here
        self.closed = False
        # FIFO lane: ordered handlers from this peer execute one at a
        # time in arrival order, but OFF the read thread, so decoding
        # (and @concurrent handlers) pipeline ahead of a slow handler.
        self._lane: deque = deque()
        self._lane_lock = threading.Lock()
        self._lane_busy = False

    def link(self, peer_role: str, link_id: str = "") -> "Connection":
        """Tag the accepted socket's chaos-link identity — services
        call this once the peer identifies itself (hello/register)."""
        _nc.register_link(self.sock, peer_role, link_id)
        return self

    def reply(self, rid: int, **kw) -> None:
        msg = dict(kw)
        msg["i"] = rid
        try:
            _send_frame(self.sock, msg, self.wlock)
        except (OSError, RpcError):
            self.closed = True

    def reply_error(self, rid: int, err: str) -> None:
        self.reply(rid, e=err)

    def push(self, method: str, **kw) -> None:
        """Server-initiated message (no correlation id)."""
        msg = dict(kw)
        msg["m"] = method
        try:
            _send_frame(self.sock, msg, self.wlock)
        except (OSError, RpcError):
            self.closed = True


class Server:
    """Threaded RPC server. ``service`` exposes ``handle_<method>``
    callables with signature (conn, rid, msg) -> reply dict | HOLD.
    Optional ``on_disconnect(conn)`` on the service is called when a
    client connection drops (daemon death detection hook)."""

    def __init__(self, service: Any, host: str = "127.0.0.1",
                 port: int = 0):
        self.service = service
        self._srv = socket.create_server((host, port))
        self.addr = self._srv.getsockname()
        self._stop = False
        self._conns: list = []
        from ray_tpu._private.thread_pool import DaemonThreadPool
        self._pool = DaemonThreadPool(128, name=f"rpc-{self.addr[1]}")
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"rpc-server-{self.addr[1]}")

    def _run_handler(self, conn: Connection, handler, rid, msg) -> None:
        try:
            out = handler(conn, rid, msg)
            if out is HOLD or rid is None:
                return
            conn.reply(rid, **(out or {}))
        except Exception as e:  # noqa: BLE001 — shipped back; the reply
            # is inside the try because an unserializable handler return
            # raises in msgpack, not in the handler
            if rid is not None:
                conn.reply_error(rid, f"{type(e).__name__}: {e}")

    def _drain_lane(self, conn: Connection) -> None:
        while True:
            with conn._lane_lock:
                if not conn._lane:
                    conn._lane_busy = False
                    return
                handler, rid, msg, t_enq = conn._lane.popleft()
            try:    # lane dwell: time queued behind same-peer requests
                from ray_tpu.util.metrics import note_queue_dwell
                note_queue_dwell("rpc.lane",
                                 time.perf_counter() - t_enq)
            except Exception:
                pass
            try:
                self._run_handler(conn, handler, rid, msg)
            except BaseException:   # never wedge the lane
                with conn._lane_lock:
                    conn._lane_busy = False
                raise

    def start(self) -> "Server":
        self._accept_thread.start()
        return self

    def _accept_loop(self) -> None:
        while not self._stop:
            try:
                sock, peer = self._srv.accept()
            except OSError:
                return
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn = Connection(sock, peer)
            self._conns.append(conn)
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True,
                             name=f"rpc-conn-{peer[1]}").start()

    def _serve_conn(self, conn: Connection) -> None:
        try:
            while not self._stop:
                msg = _recv_frame(conn.sock)
                method = msg.get("m", "")
                if _fp.ENABLED and _fp.fire(
                        "rpc.server.recv", method=method) is _fp.DROP:
                    continue    # request lost before dispatch
                rid = msg.get("i")
                with _WIRE_LOCK:
                    _WIRE_SERVER_REQS[method] = \
                        _WIRE_SERVER_REQS.get(method, 0) + 1
                handler = getattr(self.service, f"handle_{method}", None)
                if handler is None:
                    if rid is not None:
                        conn.reply_error(rid, f"no such method {method!r}")
                    continue
                if getattr(handler, "_rpc_concurrent", False):
                    # Dedicated thread, NOT the shared pool: @concurrent
                    # handlers may block for minutes (object pulls), and
                    # enough of them would exhaust the pool and stall
                    # every connection's lane drain.
                    threading.Thread(
                        target=self._run_handler,
                        args=(conn, handler, rid, msg), daemon=True,
                        name=f"rpc-conc-{method}").start()
                    continue
                with conn._lane_lock:
                    conn._lane.append((handler, rid, msg,
                                       time.perf_counter()))
                    if conn._lane_busy:
                        continue
                    conn._lane_busy = True
                self._pool.submit(lambda: self._drain_lane(conn))
        except (RpcError, OSError):
            pass
        finally:
            conn.closed = True
            try:
                conn.sock.close()
            except OSError:
                pass
            cb = getattr(self.service, "on_disconnect", None)
            if cb is not None and not self._stop:
                try:
                    cb(conn)
                except Exception:
                    pass

    def stop(self) -> None:
        self._stop = True
        try:
            self._srv.close()
        except OSError:
            pass
        for conn in self._conns:
            try:
                conn.sock.close()
            except OSError:
                pass


# ---------------------------------------------------------------------------
# core selection: ONE pair of factories gates the async rebuild. Both
# cores speak identical frames, so a threaded peer and an async peer
# interoperate on the same socket — cfg().async_core is a per-process
# choice (advertised via the async_core hello bit), not a wire version.
# ---------------------------------------------------------------------------

def serve(service: Any, host: str = "127.0.0.1", port: int = 0):
    """Build the configured server core (NOT started — call .start())."""
    from ray_tpu._private.config import cfg
    if cfg().async_core:
        from ray_tpu._private.aio import AsyncServer
        return AsyncServer(service, host=host, port=port)
    return Server(service, host=host, port=port)


def connect(addr: Tuple[str, int], timeout: float = 30.0,
            on_push: Optional[Callable[[str, Dict[str, Any]], None]]
            = None):
    """Dial with the configured client core."""
    from ray_tpu._private.config import cfg
    if cfg().async_core:
        from ray_tpu._private.aio import AsyncClient
        return AsyncClient(addr, timeout=timeout, on_push=on_push)
    return Client(addr, timeout=timeout, on_push=on_push)


def wait_for_server(addr: Tuple[str, int], timeout: float = 15.0) -> None:
    from ray_tpu._private.retry import RetryPolicy

    if timeout <= 0:
        # an exhausted budget means fail NOW (RetryPolicy reads
        # deadline_s=0 as "no deadline" and would probe forever)
        raise RpcError(f"server at {addr} did not come up in {timeout}s")

    def probe() -> None:
        with socket.create_connection(addr, timeout=1.0):
            return

    try:
        RetryPolicy(base_s=0.05, max_backoff_s=0.5,
                    deadline_s=timeout).run(
            probe, loop="rpc.wait_for_server", retry_on=(OSError,))
    except OSError:
        raise RpcError(f"server at {addr} did not come up in {timeout}s")
