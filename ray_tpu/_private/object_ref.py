"""ObjectRef: a first-class handle to a (possibly not-yet-created) value.

Parity contract (reference ``python/ray/includes/object_ref.pxi`` +
``src/ray/core_worker/reference_count.h``): refs are created by ``put`` and by
task submission; every live Python handle holds a local reference that is
released on ``__del__``; deserializing a ref inside another value creates a
borrowed reference. The distributed reference counter lives in
:mod:`ray_tpu._private.refcount`.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional

from ray_tpu._private.ids import ObjectID


class ObjectRef:
    """Handle to an immutable distributed value."""

    __slots__ = ("id", "_owner_hex", "_task_name", "_registered", "__weakref__")

    def __init__(self, object_id: ObjectID, owner_hex: str = "",
                 task_name: str = "", _register: bool = True):
        self.id = object_id
        self._owner_hex = owner_hex
        self._task_name = task_name
        self._registered = False
        if _register:
            self._add_local_ref()

    # -- refcounting hooks -------------------------------------------------
    def _add_local_ref(self):
        from ray_tpu._private import worker
        rt = worker.global_runtime()
        if rt is not None:
            rt.refcounter.add_local_ref(self.id)
            self._registered = True

    def __del__(self):
        if not self._registered:
            return
        try:
            from ray_tpu._private import worker
            rt = worker.global_runtime()
            if rt is not None:
                rt.refcounter.remove_local_ref(self.id)
        except Exception:  # interpreter teardown
            pass

    @staticmethod
    def _rehydrate(object_id: ObjectID, owner_hex: str) -> "ObjectRef":
        """Reconstruct a ref during deserialization (borrower side)."""
        return ObjectRef(object_id, owner_hex)

    # -- identity ----------------------------------------------------------
    def hex(self) -> str:
        return self.id.hex()

    def binary(self) -> bytes:
        return self.id.binary()

    def owner_hex(self) -> str:
        return self._owner_hex

    def task_name(self) -> str:
        return self._task_name

    def __hash__(self):
        return hash(self.id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.id == self.id

    def __repr__(self):
        return f"ObjectRef({self.id.hex()})"

    def __reduce__(self):
        # Plain pickling path (outside SerializationContext). Borrowers
        # re-register on rehydrate.
        return (ObjectRef._rehydrate, (self.id, self._owner_hex))

    # -- await support -----------------------------------------------------
    def __await__(self):
        return self.as_future().__await__()

    def as_future(self):
        """Return an asyncio.Future resolved with the object's value."""
        import asyncio

        loop = asyncio.get_event_loop()
        fut = loop.create_future()

        def _resolve():
            from ray_tpu._private import worker
            try:
                val = worker.global_worker().get([self])[0]
            except BaseException as e:  # noqa: BLE001 - propagate to future
                loop.call_soon_threadsafe(
                    lambda: fut.cancelled() or fut.set_exception(e))
            else:
                loop.call_soon_threadsafe(
                    lambda: fut.cancelled() or fut.set_result(val))

        threading.Thread(target=_resolve, daemon=True).start()
        return fut


class FutureTable:
    """Tracks completion events for in-flight objects.

    The execution side calls :meth:`complete` exactly once per object; waiters
    block in :meth:`wait_for`. Completion is sticky — late waiters return
    immediately.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._events: dict = {}
        self._done: set = set()
        self._callbacks: dict = {}

    def register(self, object_id: ObjectID) -> None:
        with self._lock:
            if object_id not in self._done:
                self._events.setdefault(object_id, threading.Event())

    def complete(self, object_id: ObjectID) -> None:
        with self._lock:
            self._done.add(object_id)
            ev = self._events.pop(object_id, None)
            cbs = self._callbacks.pop(object_id, [])
        if ev is not None:
            ev.set()
        for cb in cbs:
            try:
                cb(object_id)
            except Exception:
                pass

    def reset(self, object_id: ObjectID) -> None:
        """Forget completion (object lost; reconstruction will re-complete)."""
        with self._lock:
            self._done.discard(object_id)
            self._events.setdefault(object_id, threading.Event())

    def is_done(self, object_id: ObjectID) -> bool:
        with self._lock:
            return object_id in self._done

    def add_done_callback(self, object_id: ObjectID,
                          cb: Callable[[ObjectID], None]) -> None:
        with self._lock:
            if object_id in self._done:
                fire = True
            else:
                fire = False
                self._callbacks.setdefault(object_id, []).append(cb)
        if fire:
            cb(object_id)

    def wait_for(self, object_id: ObjectID,
                 timeout: Optional[float] = None) -> bool:
        with self._lock:
            if object_id in self._done:
                return True
            ev = self._events.setdefault(object_id, threading.Event())
        return ev.wait(timeout)

    def wait_any(self, object_ids: List[ObjectID], num_returns: int,
                 timeout: Optional[float] = None) -> List[ObjectID]:
        """Block until >= num_returns of object_ids are done (or timeout)."""
        cond = threading.Condition()
        ready: List[ObjectID] = []
        seen = set()

        def on_done(oid):
            with cond:
                if oid not in seen:
                    seen.add(oid)
                    ready.append(oid)
                    cond.notify_all()

        for oid in object_ids:
            self.add_done_callback(oid, on_done)

        import time
        deadline = None if timeout is None else time.monotonic() + timeout
        with cond:
            while len(ready) < num_returns:
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                cond.wait(remaining)
            return list(ready)
