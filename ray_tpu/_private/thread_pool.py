"""Reusable daemon-thread work pool.

Per-task ``threading.Thread`` spawn costs ~1ms under GIL contention and
dominated small-task throughput (PERF.md); stdlib ThreadPoolExecutor
reuses threads but makes them non-daemon, so one blocked user task would
hang interpreter exit. This pool keeps the daemon-thread semantics of
the code it replaces: threads are reused when idle, spawned on demand up
to ``max_workers``, and die with the process.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable


class DaemonThreadPool:
    def __init__(self, max_workers: int, name: str = "pool"):
        self._q: "queue.SimpleQueue[Callable[[], None]]" = queue.SimpleQueue()
        self._max = max(1, max_workers)
        self._name = name
        self._lock = threading.Lock()
        self._count = 0
        self._idle = 0      # threads blocked in _q.get()
        self._pending = 0   # queued items not yet taken by a thread

    def submit(self, fn: Callable[[], None]) -> None:
        # Spawn when queued work exceeds waiting threads — comparing
        # pending against idle (not idle > 0) closes the TOCTOU where a
        # thread that just took a long task still counts as idle and the
        # new task would starve behind it. Stale counters only ever
        # over-spawn (bounded by _max), never under-spawn.
        with self._lock:
            self._pending += 1
            spawn = self._pending > self._idle and self._count < self._max
            if spawn:
                self._count += 1
                n = self._count
        self._q.put(fn)
        if spawn:
            threading.Thread(target=self._work, daemon=True,
                             name=f"{self._name}-{n}").start()

    def _work(self) -> None:
        try:
            while True:
                with self._lock:
                    self._idle += 1
                fn = self._q.get()
                with self._lock:
                    self._idle -= 1
                    self._pending = max(0, self._pending - 1)
                try:
                    fn()
                except BaseException:  # noqa: BLE001 — submitted fns own
                    # their errors; a KeyboardInterrupt delivered to user
                    # task code must not kill the pool thread
                    pass
        finally:
            # If this thread ever dies anyway, keep capacity honest so
            # the pool respawns instead of running under phantom count.
            with self._lock:
                self._count -= 1
